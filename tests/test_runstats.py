"""Runtime-statistics feedback plane (obs/runstats.py): drift math,
history persistence + keying, the hbo=off strict no-op contract, and the
two-run acceptance loop — a workload whose static NDV estimate is 10×
wrong flips to the correct breaker engine and presize on its second run,
with zero overflow-replay waves.

Reference analog: Presto's history-based optimizer (HBO) keyed on plan
canonical hashes; here the key is the PR 5 structural fingerprint plus a
catalog snapshot token.
"""

import json
import os

import numpy as np
import pandas as pd
import pytest

from presto_tpu.catalog.memory import MemoryConnector
from presto_tpu.connector import Catalog
from presto_tpu.exec import ExecConfig
from presto_tpu.exec.runner import LocalRunner
from presto_tpu.obs import metrics as obs_metrics
from presto_tpu.obs import runstats
from presto_tpu.obs.exposition import lint_exposition
from presto_tpu.ops.grouping import partition_skew
from presto_tpu.plan.stats import exchange_lane_rows
from presto_tpu.scan import metrics as scan_metrics
from presto_tpu.server.metrics import render_metrics


@pytest.fixture
def history_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("PRESTO_TPU_CACHE_DIR", str(tmp_path))
    runstats.reset()
    scan_metrics.reset()
    yield tmp_path
    runstats.reset()


@pytest.fixture
def no_history(monkeypatch):
    monkeypatch.delenv("PRESTO_TPU_CACHE_DIR", raising=False)
    runstats.reset()
    scan_metrics.reset()
    yield
    runstats.reset()


def _skewed_catalog(n=6000):
    """All-distinct keys grouped through an EXPRESSION: the memory
    connector's exact column NDV can't see through `k % 100000`, so the
    planner falls back to the rows*0.1 heuristic — a 10× underestimate."""
    conn = MemoryConnector()
    conn.add_table("t", pd.DataFrame({
        "k": np.arange(n, dtype=np.int64),
        "v": np.ones(n, dtype=np.int64)}))
    cat = Catalog()
    cat.register("m", conn, default=True)
    return cat


SKEW_SQL = "select k % 100000 as g, sum(v) from m.t group by 1"


# -- unit: store semantics -------------------------------------------------


class TestStore:
    def test_observe_max_merge_and_drift(self, no_history):
        e1 = runstats.observe("fp1/cat", "agg_groups", "aggregate",
                              est=100.0, actual=1000.0)
        assert e1["actual"] == 1000.0 and e1["n"] == 1
        # later smaller observation keeps the high-water mark (capacity
        # consumers need the worst case), but counts the observation
        e2 = runstats.observe("fp1/cat", "agg_groups", "aggregate",
                              est=100.0, actual=400.0)
        assert e2["actual"] == 1000.0 and e2["n"] == 2
        snap = obs_metrics.STATS_DRIFT.snapshot("worker")
        counts = [s["count"] for s in snap.values()]
        assert sum(counts) >= 2

    def test_extras_merge_and_note(self, no_history):
        runstats.observe("fp2/cat", "agg_groups", "aggregate",
                         est=10.0, actual=20.0, extra={"replays": 2})
        runstats.note("fp2/cat", "agg_groups", replays=1, why="x")
        ent = runstats.lookup("fp2/cat", "agg_groups")
        assert ent["replays"] == 2.0  # max-merge
        assert ent["why"] == "x"

    def test_none_fp_is_noop(self, no_history):
        assert runstats.observe(None, "s", "op", 1.0, 2.0) is None
        runstats.note(None, "s", x=1)
        assert runstats.lookup(None, "s") is None
        assert runstats.snapshot()["history"] == {}

    def test_generation_bumps_on_mutation(self, no_history):
        g0 = runstats.generation()
        runstats.observe("fp3/cat", "s", "op", 1.0, 2.0)
        assert runstats.generation() > g0

    def test_history_jsonl_round_trip(self, history_dir):
        runstats.observe("fpA/cat", "agg_groups", "aggregate",
                         est=5.0, actual=50.0, extra={"skew": 2.5})
        path = history_dir / "hbo_history.jsonl"
        assert path.exists()
        recs = [json.loads(x) for x in path.read_text().splitlines()]
        assert recs[-1]["fp"] == "fpA/cat"
        assert recs[-1]["actual"] == 50.0
        # a fresh process (reset forces reload) sees the persisted entry
        runstats.reset()
        ent = runstats.lookup("fpA/cat", "agg_groups")
        assert ent is not None and ent["actual"] == 50.0
        assert ent["skew"] == 2.5

    def test_last_line_wins_on_load(self, history_dir):
        path = history_dir / "hbo_history.jsonl"
        path.write_text(
            json.dumps({"fp": "f/c", "site": "s", "actual": 10.0, "n": 1})
            + "\n"
            + json.dumps({"fp": "f/c", "site": "s", "actual": 99.0, "n": 2})
            + "\n" + "not json\n")
        runstats.reset()
        assert runstats.lookup("f/c", "s")["actual"] == 99.0

    def test_no_cache_dir_stays_in_memory(self, no_history):
        assert runstats.history_path() is None
        runstats.observe("fpB/cat", "s", "op", 1.0, 2.0)
        assert runstats.lookup("fpB/cat", "s")["actual"] == 2.0


class TestFingerprint:
    def test_keying_structure_and_catalog(self, no_history):
        cat = _skewed_catalog(100)
        r = LocalRunner(cat)
        qp1 = r.plan("select k from m.t where k > 5")
        qp2 = r.plan("select k from m.t where k > 9")
        qp3 = r.plan(SKEW_SQL)
        fp1 = runstats.node_fingerprint(qp1.root.child, cat)
        fp2 = runstats.node_fingerprint(qp2.root.child, cat)
        fp3 = runstats.node_fingerprint(qp3.root.child, cat)
        # literals differ but the structure is the same shape-class only
        # when the structural fingerprint says so; distinct operators
        # must never collide
        assert fp1 != fp3 and fp2 != fp3
        # same node → memoized, stable
        assert runstats.node_fingerprint(qp1.root.child, cat) == fp1
        # data change flips the catalog token half of every key
        tok_before = runstats.catalog_token(cat)
        cat.connectors["m"].add_table("t2", pd.DataFrame({"x": [1, 2]}))
        assert runstats.catalog_token(cat) != tok_before

    def test_fingerprint_strips_config_suffix(self, no_history):
        cat = _skewed_catalog(100)

        class N:
            pass

        n = N()
        n.__dict__["_program_ns"] = "a" * 40 + "f" * 16  # sha + config fp
        fp = runstats.node_fingerprint(n, cat)
        assert fp.startswith("a" * 24 + "/")


class TestMetricRows:
    def test_exposition_families_and_lint(self, no_history):
        runstats.observe("fpC/cat", "agg_groups", "aggregate", 1.0, 4.0)
        runstats.record_flip("breaker_engine")
        runstats.record_correction("agg_presize")
        rows = runstats.metric_rows({"plane": "worker"})
        doc = render_metrics(rows)
        assert lint_exposition(doc) == []
        assert 'presto_tpu_hbo_observations_total{plane="worker",' \
               'site="agg_groups"} 1' in doc
        assert 'presto_tpu_hbo_would_flip_total{plane="worker",' \
               'site="breaker_engine"} 1' in doc
        assert 'presto_tpu_hbo_corrections_total{plane="worker",' \
               'site="agg_presize"} 1' in doc
        assert "presto_tpu_hbo_history_entries" in doc

    def test_drift_histogram_family_renders(self, no_history):
        runstats.observe("fpD/cat", "scan_rows", "tablescan", 10.0, 20.0)
        doc = "\n".join(obs_metrics.STATS_DRIFT.render("worker")) + "\n"
        assert lint_exposition(doc) == []
        assert "presto_tpu_stats_drift_ratio_bucket" in doc


# -- unit: planner hooks ---------------------------------------------------


class TestPlannerHooks:
    def test_exchange_lane_rows_observed_override(self):
        static = exchange_lane_rows(10000.0, 100.0, 4)
        observed = exchange_lane_rows(10000.0, 100.0, 4,
                                      observed_lane_rows=40.0)
        assert observed == 50.0  # 40 × 1.25 headroom
        assert observed != static
        # None / 0 fall through to the static path
        assert exchange_lane_rows(10000.0, 100.0, 4,
                                  observed_lane_rows=None) == static
        assert exchange_lane_rows(10000.0, 100.0, 4,
                                  observed_lane_rows=0.0) == static

    def test_partition_skew(self):
        assert partition_skew([10, 10, 10, 10]) == 1.0
        assert partition_skew([40, 0, 0, 0]) == 1.0  # one live partition
        assert partition_skew([30, 10]) == pytest.approx(1.5)
        assert partition_skew([]) == 1.0


# -- acceptance: the two-run feedback loop ---------------------------------


class TestFeedbackLoop:
    def test_run1_observes_drift_run2_corrects(self, history_dir):
        cat = _skewed_catalog()
        r1 = LocalRunner(cat, ExecConfig(hbo="observe"))
        txt1 = r1.explain_analyze(SKEW_SQL)
        # run 1: static estimate 600 groups → hash engine, presize 4096;
        # actual 6000 distinct groups → ≥1 overflow-replay wave and a 10×
        # drift annotation
        assert "engine=hash" in txt1
        assert "drift=10x" in txt1
        assert r1.last_stats.get("breaker.replay_waves", 0) >= 1
        snap = runstats.snapshot()
        assert snap["observations"].get("agg_groups") == 1
        assert snap["would_flip"].get("breaker_engine") == 1
        ent = [e for k, e in snap["history"].items()
               if k.endswith("|agg_groups")]
        assert ent and ent[0]["actual"] == 6000.0 and ent[0]["est"] == 600.0

        # run 2 (fresh runner, same structure): history flips the engine
        # choice, presizes past the observed group count, zero waves
        r2 = LocalRunner(cat, ExecConfig(hbo="correct"))
        txt2 = r2.explain_analyze(SKEW_SQL)
        assert "(hbo: observed)" in txt2
        assert "engine=sort" in txt2
        assert r2.last_stats.get("breaker.replay_waves", 0) == 0
        corr = runstats.snapshot()["corrections"]
        assert corr.get("breaker_engine", 0) >= 1
        assert corr.get("agg_presize", 0) >= 1
        # same answer both runs (group-by output order is engine-defined)
        d1 = r1.run(SKEW_SQL).sort_values("g").reset_index(drop=True)
        d2 = r2.run(SKEW_SQL).sort_values("g").reset_index(drop=True)
        assert d1.equals(d2)

    def test_hbo_off_is_strict_noop(self, history_dir):
        cat = _skewed_catalog()
        r = LocalRunner(cat, ExecConfig(hbo="off"))
        txt = r.explain_analyze(SKEW_SQL)
        # pre-HBO behavior: static choice, no provenance, no drift marker,
        # nothing observed, nothing persisted
        assert "engine=hash" in txt
        assert "(hbo: observed)" not in txt
        assert "drift=" not in txt
        snap = runstats.snapshot()
        assert snap["history"] == {}
        assert snap["observations"] == {}
        assert not (history_dir / "hbo_history.jsonl").exists()
        # ...but replay-wave telemetry still counts (the wave happened)
        assert r.last_stats.get("breaker.replay_waves", 0) >= 1

    def test_observe_mode_never_changes_decisions(self, history_dir):
        cat = _skewed_catalog()
        r1 = LocalRunner(cat, ExecConfig(hbo="observe"))
        r1.run_batch(SKEW_SQL)
        # warm history, but observe-mode runs keep using static estimates
        r2 = LocalRunner(cat, ExecConfig(hbo="observe"))
        txt = r2.explain_analyze(SKEW_SQL)
        assert "engine=hash" in txt
        assert "(hbo: observed)" not in txt

    def test_session_property_plumbs_hbo(self):
        from presto_tpu.server.session import Session, SessionPropertyError

        s = Session()
        assert s.exec_config().hbo == "observe"
        s.set("hbo", "CORRECT")
        assert s.exec_config().hbo == "correct"
        with pytest.raises(SessionPropertyError):
            s.set("hbo", "sometimes")


class TestHistoryCompaction:
    """Satellite of the devprof PR: the history store ages out on load
    (TTL + entry cap) and `python -m presto_tpu.obs.runstats --compact`
    rewrites the append-only JSONL to one line per live entry."""

    def _write(self, path, records):
        with open(path, "a") as fh:
            for rec in records:
                fh.write(json.dumps(rec) + "\n")

    def test_ttl_drops_stale_entries_on_load(self, history_dir):
        import time as _time

        now = _time.time()
        path = history_dir / "hbo_history.jsonl"
        self._write(path, [
            {"fp": "old", "site": "s", "actual_rows": 1,
             "ts": now - 100 * 86400.0},
            {"fp": "fresh", "site": "s", "actual_rows": 2, "ts": now},
            # ts-less records predate the TTL stamp — kept, not dropped
            {"fp": "legacy", "site": "s", "actual_rows": 3},
        ])
        runstats.reset()  # force lazy reload with the default TTL
        assert runstats.lookup("old", "s") is None
        assert runstats.lookup("fresh", "s")["actual_rows"] == 2
        assert runstats.lookup("legacy", "s")["actual_rows"] == 3

    def test_entry_cap_keeps_newest(self, history_dir):
        import time as _time

        now = _time.time()
        path = history_dir / "hbo_history.jsonl"
        self._write(path, [
            {"fp": f"fp{i}", "site": "s", "actual_rows": i, "ts": now + i}
            for i in range(6)])
        res = runstats.compact(max_entries=2)
        assert res["lines_before"] == 6 and res["entries"] == 2
        assert runstats.lookup("fp5", "s") is not None
        assert runstats.lookup("fp4", "s") is not None
        assert runstats.lookup("fp0", "s") is None
        # the file itself was rewritten to the survivors
        assert len(path.read_text().splitlines()) == 2

    def test_compact_rewrites_superseded_lines(self, history_dir):
        # same (fp, site) observed repeatedly: append-only bloat, one
        # live entry
        for actual in (10, 20, 30):
            runstats.observe("fp", "s", "groupby", est=5, actual=actual)
        path = history_dir / "hbo_history.jsonl"
        assert len(path.read_text().splitlines()) == 3
        res = runstats.compact()
        assert res["lines_before"] == 3 and res["entries"] == 1
        assert len(path.read_text().splitlines()) == 1
        ent = runstats.lookup("fp", "s")
        assert ent["actual"] == 30.0  # the merged (latest/max) entry wins

    def test_cli_compact(self, history_dir, capsys):
        runstats.observe("fp", "s", "groupby", est=5, actual=7)
        runstats.observe("fp", "s", "groupby", est=5, actual=9)
        assert runstats.main(["--compact"]) == 0
        out = capsys.readouterr().out
        assert "compacted" in out and "2 lines -> 1 entries" in out

    def test_cli_without_cache_dir(self, no_history, capsys):
        assert runstats.main(["--compact"]) == 1
        assert "PRESTO_TPU_CACHE_DIR is not set" in capsys.readouterr().out
