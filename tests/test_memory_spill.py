"""Memory accounting + spill (reference: presto-memory-context,
MemoryPool/ClusterMemoryManager, MemoryRevokingScheduler, spiller/,
SpillableHashAggregationBuilder, HashBuilderOperator spill states)."""

import numpy as np
import pandas as pd
import pytest

from presto_tpu.batch import Batch
from presto_tpu.connector import Catalog
from presto_tpu.catalog.memory import MemoryConnector
from presto_tpu.exec import ExecConfig, LocalRunner
from presto_tpu.memory import (
    AggregatedMemoryContext,
    ExceededMemoryLimit,
    LocalMemoryContext,
    MemoryPool,
    batch_device_bytes,
)
from presto_tpu.spiller import SpillManager

from conftest import assert_frames_match


def test_pool_reserve_free_peak():
    pool = MemoryPool(1000)
    c = LocalMemoryContext(pool, "op")
    c.set_bytes(400)
    assert pool.reserved == 400
    c.set_bytes(100)
    assert pool.reserved == 100
    assert pool.peak == 400
    c.close()
    assert pool.reserved == 0


def test_pool_limit_enforced():
    pool = MemoryPool(1000)
    c = LocalMemoryContext(pool, "op")
    with pytest.raises(ExceededMemoryLimit):
        c.set_bytes(2000)


def test_pool_revocation():
    pool = MemoryPool(1000, revoke_threshold=0.8, revoke_target=0.3)
    victim = LocalMemoryContext(pool, "agg")
    victim.set_bytes(700)
    revoked = []

    def revoker(need):
        revoked.append(need)
        freed = victim.bytes
        victim.set_bytes(0)
        return freed

    pool.add_revoker(revoker)
    other = LocalMemoryContext(pool, "join")
    other.set_bytes(200)  # 700+200 > 800 → revoke down toward 300
    assert revoked, "revoker not invoked"
    assert pool.reserved == 200


def test_aggregated_context_rollup():
    pool = MemoryPool(None)
    agg = AggregatedMemoryContext(pool, "task")
    a, b = agg.new_local("op1"), agg.new_local("op2")
    a.set_bytes(10)
    b.set_bytes(20)
    assert agg.bytes == 30
    agg.close()
    assert pool.reserved == 0


def test_spill_file_roundtrip(tmp_path, rng):
    from presto_tpu.types import BIGINT, DOUBLE

    sm = SpillManager(str(tmp_path))
    sp = sm.partitioning_spiller(["k"], 4, "t")
    n = 1000
    k = rng.integers(0, 50, n)
    v = rng.normal(size=n)
    b = Batch.from_numpy({"k": k, "v": v}, {"k": BIGINT, "v": DOUBLE})
    sp.spill(b)
    sp.spill(b)
    back_k, back_v = [], []
    seen_parts = 0
    for p in range(4):
        batches = list(sp.read_partition(p))
        if batches:
            seen_parts += 1
        for rb in batches:
            d = rb.to_pydict()
            back_k.extend(d["k"])
            back_v.extend(d["v"])
    assert seen_parts > 1  # actually partitioned
    assert sorted(back_k) == sorted(list(k) * 2)
    np.testing.assert_allclose(sorted(back_v), sorted(list(v) * 2))
    sp.close()


@pytest.fixture(scope="module")
def spill_tables(rng):
    n = 60_000
    cat = Catalog()
    conn = MemoryConnector()
    conn.add_table("facts", pd.DataFrame({
        "g": rng.integers(0, 20_000, n),
        "v": rng.normal(size=n),
        "k": rng.integers(0, 5_000, n),
    }))
    conn.add_table("dim", pd.DataFrame({
        "id": np.arange(5_000),
        "w": rng.normal(size=5_000),
    }))
    cat.register("m", conn, default=True)
    return cat


def _runners(cat, pool_bytes):
    unlimited = LocalRunner(cat, ExecConfig(batch_rows=1 << 13))
    limited = LocalRunner(cat, ExecConfig(
        batch_rows=1 << 13, memory_pool_bytes=pool_bytes,
        spill_partitions=4,
    ))
    return unlimited, limited


def test_aggregation_spills_and_matches(spill_tables):
    sql = "select g, sum(v) as s, count(*) as c, avg(v) as a from facts group by g"
    unlimited, limited = _runners(spill_tables, 1 << 20)
    exp = unlimited.run(sql)
    ctx_probe = {}
    # run limited and capture that spill actually happened
    from presto_tpu.exec.runtime import ExecContext, run_plan

    qp = limited.plan(sql)
    ctx = ExecContext(limited.catalog, limited.config)
    got = run_plan(qp, ctx).to_pandas()
    assert ctx.spill_manager.spill_count > 0, "expected the aggregation to spill"
    assert_frames_match(got, exp, sort_by=["g"])


def test_join_build_spills_and_matches(spill_tables):
    sql = """select dim.w, facts.v from facts join dim on facts.k = dim.id
             where facts.g < 1000"""
    unlimited, limited = _runners(spill_tables, 100 << 10)
    exp = unlimited.run(sql)
    from presto_tpu.exec.runtime import ExecContext, run_plan

    qp = limited.plan(sql)
    ctx = ExecContext(limited.catalog, limited.config)
    got = run_plan(qp, ctx).to_pandas()
    assert ctx.spill_manager.spill_count >= 2  # build + probe spillers
    assert_frames_match(got, exp, sort_by=["w", "v"])


def test_left_join_spill_preserves_outer_rows(spill_tables):
    # k ranges to 5000, dim ids cover all → add filter making some unmatched
    sql = """select facts.k, dim.w from facts left join dim
             on facts.k = dim.id and dim.w > 0.5 where facts.g < 300"""
    unlimited, limited = _runners(spill_tables, 100 << 10)
    exp = unlimited.run(sql)
    got = limited.run(sql)
    assert_frames_match(got, exp, sort_by=["k", "w"])


def test_spilled_join_string_keys_cross_dictionary(rng):
    """Spill routing must hash string CONTENT, not dictionary codes: the two
    sides are encoded against different dictionaries, so equal strings have
    different codes — code-hash routing would send matches to different
    buckets and silently drop rows."""
    n = 40_000
    keys_probe = [f"k{i:05d}" for i in rng.integers(0, 3000, n)]
    # build dictionary has a DIFFERENT value set (superset w/ extra values)
    dim_keys = [f"k{i:05d}" for i in range(4000)]
    cat = Catalog()
    conn = MemoryConnector()
    conn.add_table("f", pd.DataFrame({"sk": keys_probe, "v": rng.normal(size=n)}))
    conn.add_table("d", pd.DataFrame({"dk": dim_keys,
                                      "w": rng.normal(size=len(dim_keys))}))
    cat.register("m", conn, default=True)
    sql = "select d.w, f.v from f join d on f.sk = d.dk"
    unlimited = LocalRunner(cat, ExecConfig(batch_rows=1 << 13))
    limited = LocalRunner(cat, ExecConfig(batch_rows=1 << 13,
                                          memory_pool_bytes=48 << 10,
                                          spill_partitions=4))
    exp = unlimited.run(sql)
    from presto_tpu.exec.runtime import ExecContext, run_plan

    qp = limited.plan(sql)
    ctx = ExecContext(limited.catalog, limited.config)
    got = run_plan(qp, ctx).to_pandas()
    assert ctx.spill_manager.spill_count >= 2, "join did not spill"
    assert len(got) == len(exp) == n  # every probe row matches
    assert_frames_match(got, exp, sort_by=["w", "v"])


def test_memory_limit_without_spill_fails(spill_tables):
    runner = LocalRunner(spill_tables, ExecConfig(
        batch_rows=1 << 13, memory_pool_bytes=512 << 10, spill_enabled=False,
    ))
    with pytest.raises(ExceededMemoryLimit):
        runner.run("select g, sum(v) as s from facts group by g")


def test_distributed_query_with_spill(spill_tables):
    from presto_tpu.server.coordinator import DistributedRunner
    from presto_tpu.server.worker import Worker

    unlimited = LocalRunner(spill_tables, ExecConfig(batch_rows=1 << 13))
    sql = "select g, sum(v) as s from facts group by g"
    exp = unlimited.run(sql)
    r = DistributedRunner(spill_tables, n_workers=2,
                          config=ExecConfig(batch_rows=1 << 13,
                                            memory_pool_bytes=256 << 10,
                                            spill_partitions=4))
    try:
        assert all(w.memory_pool.limit == 256 << 10 for w in r.workers)
        got = r.run(sql)
        assert_frames_match(got, exp, sort_by=["g"])
        assert any(w.spill_manager.spill_count > 0 for w in r.workers)
        # status endpoint reports memory + spill
        st = r.workers[0].status()
        assert "memory" in st and "spilledBytes" in st
    finally:
        r.close()
