"""Memory accounting + spill (reference: presto-memory-context,
MemoryPool/ClusterMemoryManager, MemoryRevokingScheduler, spiller/,
SpillableHashAggregationBuilder, HashBuilderOperator spill states)."""

import numpy as np
import pandas as pd
import pytest

from presto_tpu.batch import Batch
from presto_tpu.connector import Catalog
from presto_tpu.catalog.memory import MemoryConnector
from presto_tpu.exec import ExecConfig, LocalRunner
from presto_tpu.memory import (
    AggregatedMemoryContext,
    ExceededMemoryLimit,
    LocalMemoryContext,
    MemoryPool,
    batch_device_bytes,
)
from presto_tpu.spiller import SpillManager

from conftest import assert_frames_match


def test_pool_reserve_free_peak():
    pool = MemoryPool(1000)
    c = LocalMemoryContext(pool, "op")
    c.set_bytes(400)
    assert pool.reserved == 400
    c.set_bytes(100)
    assert pool.reserved == 100
    assert pool.peak == 400
    c.close()
    assert pool.reserved == 0


def test_pool_limit_enforced():
    pool = MemoryPool(1000)
    c = LocalMemoryContext(pool, "op")
    with pytest.raises(ExceededMemoryLimit):
        c.set_bytes(2000)


def test_pool_revocation():
    pool = MemoryPool(1000, revoke_threshold=0.8, revoke_target=0.3)
    victim = LocalMemoryContext(pool, "agg")
    victim.set_bytes(700)
    revoked = []

    def revoker(need):
        revoked.append(need)
        freed = victim.bytes
        victim.set_bytes(0)
        return freed

    pool.add_revoker(revoker)
    other = LocalMemoryContext(pool, "join")
    other.set_bytes(200)  # 700+200 > 800 → revoke down toward 300
    assert revoked, "revoker not invoked"
    assert pool.reserved == 200


def test_aggregated_context_rollup():
    pool = MemoryPool(None)
    agg = AggregatedMemoryContext(pool, "task")
    a, b = agg.new_local("op1"), agg.new_local("op2")
    a.set_bytes(10)
    b.set_bytes(20)
    assert agg.bytes == 30
    agg.close()
    assert pool.reserved == 0


def test_spill_file_roundtrip(tmp_path, rng):
    from presto_tpu.types import BIGINT, DOUBLE

    sm = SpillManager(str(tmp_path))
    sp = sm.partitioning_spiller(["k"], 4, "t")
    n = 1000
    k = rng.integers(0, 50, n)
    v = rng.normal(size=n)
    b = Batch.from_numpy({"k": k, "v": v}, {"k": BIGINT, "v": DOUBLE})
    sp.spill(b)
    sp.spill(b)
    back_k, back_v = [], []
    seen_parts = 0
    for p in range(4):
        batches = list(sp.read_partition(p))
        if batches:
            seen_parts += 1
        for rb in batches:
            d = rb.to_pydict()
            back_k.extend(d["k"])
            back_v.extend(d["v"])
    assert seen_parts > 1  # actually partitioned
    assert sorted(back_k) == sorted(list(k) * 2)
    np.testing.assert_allclose(sorted(back_v), sorted(list(v) * 2))
    sp.close()


@pytest.fixture(scope="module")
def spill_tables(rng):
    n = 60_000
    cat = Catalog()
    conn = MemoryConnector()
    conn.add_table("facts", pd.DataFrame({
        "g": rng.integers(0, 20_000, n),
        "v": rng.normal(size=n),
        "k": rng.integers(0, 5_000, n),
    }))
    conn.add_table("dim", pd.DataFrame({
        "id": np.arange(5_000),
        "w": rng.normal(size=5_000),
    }))
    cat.register("m", conn, default=True)
    return cat


def _runners(cat, pool_bytes):
    unlimited = LocalRunner(cat, ExecConfig(batch_rows=1 << 13))
    limited = LocalRunner(cat, ExecConfig(
        batch_rows=1 << 13, memory_pool_bytes=pool_bytes,
        spill_partitions=4,
    ))
    return unlimited, limited


def test_aggregation_spills_and_matches(spill_tables):
    sql = "select g, sum(v) as s, count(*) as c, avg(v) as a from facts group by g"
    unlimited, limited = _runners(spill_tables, 1 << 20)
    exp = unlimited.run(sql)
    ctx_probe = {}
    # run limited and capture that spill actually happened
    from presto_tpu.exec.runtime import ExecContext, run_plan

    qp = limited.plan(sql)
    ctx = ExecContext(limited.catalog, limited.config)
    got = run_plan(qp, ctx).to_pandas()
    assert ctx.spill_manager.spill_count > 0, "expected the aggregation to spill"
    assert_frames_match(got, exp, sort_by=["g"])


def test_join_build_spills_and_matches(spill_tables):
    sql = """select dim.w, facts.v from facts join dim on facts.k = dim.id
             where facts.g < 1000"""
    unlimited, limited = _runners(spill_tables, 100 << 10)
    exp = unlimited.run(sql)
    from presto_tpu.exec.runtime import ExecContext, run_plan

    qp = limited.plan(sql)
    ctx = ExecContext(limited.catalog, limited.config)
    got = run_plan(qp, ctx).to_pandas()
    assert ctx.spill_manager.spill_count >= 2  # build + probe spillers
    assert_frames_match(got, exp, sort_by=["w", "v"])


def test_left_join_spill_preserves_outer_rows(spill_tables):
    # k ranges to 5000, dim ids cover all → add filter making some unmatched
    sql = """select facts.k, dim.w from facts left join dim
             on facts.k = dim.id and dim.w > 0.5 where facts.g < 300"""
    unlimited, limited = _runners(spill_tables, 100 << 10)
    exp = unlimited.run(sql)
    got = limited.run(sql)
    assert_frames_match(got, exp, sort_by=["k", "w"])


def test_spilled_join_string_keys_cross_dictionary(rng):
    """Spill routing must hash string CONTENT, not dictionary codes: the two
    sides are encoded against different dictionaries, so equal strings have
    different codes — code-hash routing would send matches to different
    buckets and silently drop rows."""
    n = 40_000
    keys_probe = [f"k{i:05d}" for i in rng.integers(0, 3000, n)]
    # build dictionary has a DIFFERENT value set (superset w/ extra values)
    dim_keys = [f"k{i:05d}" for i in range(4000)]
    cat = Catalog()
    conn = MemoryConnector()
    conn.add_table("f", pd.DataFrame({"sk": keys_probe, "v": rng.normal(size=n)}))
    conn.add_table("d", pd.DataFrame({"dk": dim_keys,
                                      "w": rng.normal(size=len(dim_keys))}))
    cat.register("m", conn, default=True)
    sql = "select d.w, f.v from f join d on f.sk = d.dk"
    unlimited = LocalRunner(cat, ExecConfig(batch_rows=1 << 13))
    limited = LocalRunner(cat, ExecConfig(batch_rows=1 << 13,
                                          memory_pool_bytes=48 << 10,
                                          spill_partitions=4))
    exp = unlimited.run(sql)
    from presto_tpu.exec.runtime import ExecContext, run_plan

    qp = limited.plan(sql)
    ctx = ExecContext(limited.catalog, limited.config)
    got = run_plan(qp, ctx).to_pandas()
    assert ctx.spill_manager.spill_count >= 2, "join did not spill"
    assert len(got) == len(exp) == n  # every probe row matches
    assert_frames_match(got, exp, sort_by=["w", "v"])


def test_memory_limit_without_spill_fails(spill_tables):
    runner = LocalRunner(spill_tables, ExecConfig(
        batch_rows=1 << 13, memory_pool_bytes=512 << 10, spill_enabled=False,
    ))
    with pytest.raises(ExceededMemoryLimit):
        runner.run("select g, sum(v) as s from facts group by g")


def test_distributed_query_with_spill(spill_tables):
    from presto_tpu.server.coordinator import DistributedRunner
    from presto_tpu.server.worker import Worker

    unlimited = LocalRunner(spill_tables, ExecConfig(batch_rows=1 << 13))
    sql = "select g, sum(v) as s from facts group by g"
    exp = unlimited.run(sql)
    r = DistributedRunner(spill_tables, n_workers=2,
                          config=ExecConfig(batch_rows=1 << 13,
                                            memory_pool_bytes=256 << 10,
                                            spill_partitions=4))
    try:
        assert all(w.memory_pool.limit == 256 << 10 for w in r.workers)
        got = r.run(sql)
        assert_frames_match(got, exp, sort_by=["g"])
        assert any(w.spill_manager.spill_count > 0 for w in r.workers)
        # status endpoint reports memory + spill
        st = r.workers[0].status()
        assert "memory" in st and "spilledBytes" in st
    finally:
        r.close()


# -- PR 15: dynamic hybrid hash spill plane --------------------------------


def test_spill_file_names_never_collide(tmp_path, rng):
    """Spill paths derive from a process-monotonic counter, not id(self):
    two spillers alive at different times (id() is recycled after GC) must
    never map the same tag+partition to the same path."""
    sm = SpillManager(str(tmp_path))
    a = sm.partitioning_spiller(["k"], 4, "t")
    paths_a = {f.path for f in a.files}
    a.close()
    b = sm.partitioning_spiller(["k"], 4, "t")
    paths_b = {f.path for f in b.files}
    b.close()
    assert len(paths_a) == len(paths_b) == 4
    assert not (paths_a & paths_b)
    f1, f2 = sm.spill_file("x"), sm.spill_file("x")
    assert f1.path != f2.path
    f1.close()
    f2.close()


def _one_spill_file(tmp_path, rng, n=500):
    from presto_tpu.types import BIGINT, DOUBLE

    sm = SpillManager(str(tmp_path))
    f = sm.spill_file("crc")
    b = Batch.from_numpy({"k": rng.integers(0, 50, n), "v": rng.normal(size=n)},
                         {"k": BIGINT, "v": DOUBLE})
    f.append(b)
    f.append(b)
    f.finish_writing()
    return f


def test_spill_crc_bit_flip_detected(tmp_path, rng):
    """A flipped bit in a spilled page must surface as a structured
    SpillCorruption naming the file and page, never as garbage rows."""
    from presto_tpu.spiller import SpillCorruption

    f = _one_spill_file(tmp_path, rng)
    with open(f.path, "r+b") as fh:
        fh.seek(40)  # inside the first page's payload
        byte = fh.read(1)
        fh.seek(40)
        fh.write(bytes([byte[0] ^ 0xFF]))
    with pytest.raises(SpillCorruption, match="crc32 mismatch") as ei:
        list(f.read())
    assert ei.value.path == f.path
    assert ei.value.page == 0


def test_spill_truncation_detected(tmp_path, rng):
    """A torn write (file truncated mid-page) must fail the replay loudly
    with the framing diagnosis, not silently drop the tail rows."""
    import os as _os

    from presto_tpu.spiller import SpillCorruption

    f = _one_spill_file(tmp_path, rng)
    size = _os.path.getsize(f.path)
    with open(f.path, "r+b") as fh:
        fh.truncate(size - 7)
    with pytest.raises(SpillCorruption, match="truncated"):
        list(f.read())


def test_spill_leak_guard_on_mid_spill_failure(rng):
    """A query killed mid-spill (spill-directory byte budget exhausted)
    must not strand spill files: run_plan's teardown closes and unlinks
    every spill resource the context ever opened."""
    import os as _os

    from presto_tpu.exec.runtime import ExecContext, run_plan
    from presto_tpu.spiller import SpillLimitExceeded

    n = 60_000
    cat = Catalog()
    conn = MemoryConnector()
    conn.add_table("f", pd.DataFrame({"k": rng.integers(0, 5_000, n),
                                      "v": rng.normal(size=n)}))
    conn.add_table("d", pd.DataFrame({"id": np.arange(5_000),
                                      "w": rng.normal(size=5_000)}))
    cat.register("m", conn, default=True)
    r = LocalRunner(cat, ExecConfig(
        batch_rows=1 << 13, memory_pool_bytes=100 << 10, spill_partitions=4,
        spill_dir_budget_bytes=24 << 10))
    qp = r.plan("select d.w, f.v from f join d on f.k = d.id")
    ctx = ExecContext(cat, r.config)
    with pytest.raises(SpillLimitExceeded, match="byte budget"):
        run_plan(qp, ctx)
    assert ctx.spill_manager.in_use_bytes == 0
    assert _os.listdir(ctx.spill_manager.dir) == []


def test_spill_leak_guard_on_cancel(spill_tables):
    """An abandoned (canceled) query leaves its spill generators unclosed;
    task teardown's cleanup_spill must still unlink every spill file."""
    import os as _os

    from presto_tpu.exec.runtime import ExecContext, execute_node

    cfg = ExecConfig(batch_rows=1 << 13, memory_pool_bytes=100 << 10,
                     spill_partitions=4)
    r = LocalRunner(spill_tables, cfg)
    qp = r.plan("select dim.w, facts.v from facts join dim on facts.k = dim.id")
    ctx = ExecContext(spill_tables, cfg)
    stream = execute_node(qp.root.child, ctx)
    next(stream)  # partial consumption: the join has spilled and is replaying
    assert ctx.spill_resources, "join did not spill"
    assert ctx.spill_manager.in_use_bytes > 0
    ctx.cleanup_spill()  # what TaskExecution/run_plan teardown calls
    assert ctx.spill_manager.in_use_bytes == 0
    assert _os.listdir(ctx.spill_manager.dir) == []


# -- skew-adversarial matrix ----------------------------------------------


def test_spilled_join_role_reversal_on_skewed_build(rng):
    """One-hot build keys: 95% of build rows share one key, so no amount of
    next-hash-bit splitting shrinks the hot partition. Its probe partition
    is small — replay must REVERSE roles (build the probe side, stream the
    hot side) instead of recursing to the depth bound and failing."""
    from presto_tpu.exec.runtime import ExecContext, run_plan

    n_build, n_probe = 24_000, 32_000
    bk = np.where(rng.random(n_build) < 0.95, 7,
                  rng.integers(0, 2_000, n_build)).astype(np.int64)
    cat = Catalog()
    conn = MemoryConnector()
    conn.add_table("probe", pd.DataFrame({
        "k": rng.integers(0, 2_000, n_probe).astype(np.int64),
        "v": rng.normal(size=n_probe)}))
    conn.add_table("build", pd.DataFrame({"bk": bk,
                                          "w": rng.normal(size=n_build)}))
    cat.register("m", conn, default=True)
    sql = "select probe.v, build.w from probe join build on probe.k = build.bk"
    exp = LocalRunner(cat, ExecConfig(batch_rows=1 << 13)).run(sql)
    limited = LocalRunner(cat, ExecConfig(
        batch_rows=1 << 13, memory_pool_bytes=96 << 10, spill_partitions=4,
        spill_max_depth=2))
    qp = limited.plan(sql)
    ctx = ExecContext(cat, limited.config)
    got = run_plan(qp, ctx).to_pandas()
    assert ctx.stats.get("spill.role_reversals", 0) > 0, \
        "hot partition did not reverse roles"
    assert ctx.stats.get("spill.repartitions", 0) > 0
    assert_frames_match(got, exp, sort_by=["v", "w"])


def test_spilled_join_depth_bound_fails_structured(rng):
    """Identical keys on BOTH sides: hash bits can never split the hot
    partition and role reversal cannot rescue it (the probe side is just
    as hot) — recursion must stop at spill_max_depth with a structured
    SPILL_LIMIT_EXCEEDED, not loop forever or OOM."""
    import os as _os

    from presto_tpu.exec.runtime import ExecContext, run_plan
    from presto_tpu.spiller import SpillLimitExceeded

    n = 40_000
    cat = Catalog()
    conn = MemoryConnector()
    conn.add_table("a", pd.DataFrame({"k": np.zeros(n, dtype=np.int64),
                                      "v": rng.normal(size=n)}))
    conn.add_table("b", pd.DataFrame({"j": np.zeros(n, dtype=np.int64),
                                      "w": rng.normal(size=n)}))
    cat.register("m", conn, default=True)
    r = LocalRunner(cat, ExecConfig(
        batch_rows=1 << 13, memory_pool_bytes=128 << 10, spill_partitions=4,
        spill_max_depth=2))
    qp = r.plan("select a.v, b.w from a join b on a.k = b.j")
    ctx = ExecContext(cat, r.config)
    with pytest.raises(SpillLimitExceeded, match="max recursion depth"):
        run_plan(qp, ctx)
    # the structured failure still tears down cleanly
    assert _os.listdir(ctx.spill_manager.dir) == []


def test_spilled_join_zero_row_partitions(rng):
    """NDV below the partition count leaves most partitions empty, and
    probe-only keys leave build partitions empty while their probe side is
    populated — both must replay cleanly (skip, no output) not crash."""
    from presto_tpu.exec.runtime import ExecContext, run_plan

    cat = Catalog()
    conn = MemoryConnector()
    conn.add_table("bl", pd.DataFrame({
        "k": np.repeat(np.arange(3, dtype=np.int64), 800),
        "w": rng.normal(size=2_400)}))
    conn.add_table("pr", pd.DataFrame({
        "j": rng.integers(0, 9, 20_000).astype(np.int64),
        "v": rng.normal(size=20_000)}))
    cat.register("m", conn, default=True)
    sql = "select pr.v, bl.w from pr join bl on pr.j = bl.k"
    exp = LocalRunner(cat, ExecConfig(batch_rows=1 << 13)).run(sql)
    limited = LocalRunner(cat, ExecConfig(
        batch_rows=1 << 13, memory_pool_bytes=32 << 10, spill_partitions=8,
        join_spill_budget_bytes=64 << 10))
    qp = limited.plan(sql)
    ctx = ExecContext(cat, limited.config)
    got = run_plan(qp, ctx).to_pandas()
    assert ctx.spill_manager.spill_count >= 2, "join did not spill"
    assert_frames_match(got, exp, sort_by=["v", "w"])


@pytest.mark.parametrize("ndv,dup", [(50, 160), (4_000, 2)])
def test_spilled_join_ndv_duplication_matrix(rng, ndv, dup):
    """Duplication-vs-NDV sweep: heavy duplication (few fat keys) and high
    NDV (many thin keys) stress opposite corners of the partitioner; both
    must match the in-memory oracle bit-for-bit on values."""
    from presto_tpu.exec.runtime import ExecContext, run_plan

    bk = np.repeat(np.arange(ndv, dtype=np.int64), dup)
    cat = Catalog()
    conn = MemoryConnector()
    conn.add_table("bl", pd.DataFrame({"k": bk,
                                       "w": rng.normal(size=len(bk))}))
    conn.add_table("pr", pd.DataFrame({
        "j": rng.integers(0, ndv, 12_000).astype(np.int64),
        "v": rng.normal(size=12_000)}))
    cat.register("m", conn, default=True)
    sql = "select pr.v, bl.w from pr join bl on pr.j = bl.k"
    exp = LocalRunner(cat, ExecConfig(batch_rows=1 << 13)).run(sql)
    limited = LocalRunner(cat, ExecConfig(
        batch_rows=1 << 13, memory_pool_bytes=48 << 10, spill_partitions=4))
    qp = limited.plan(sql)
    ctx = ExecContext(cat, limited.config)
    got = run_plan(qp, ctx).to_pandas()
    assert ctx.spill_manager.spill_count >= 2, "join did not spill"
    assert_frames_match(got, exp, sort_by=["v", "w"])


def test_hbo_seeds_spill_partitions_fewer_waves(tmp_path, monkeypatch, rng):
    """Two-run acceptance loop: run 1 under-estimates the partition count
    and pays repartition waves; run 2 with hbo=correct seeds the converged
    leaf count from history and must see STRICTLY fewer waves."""
    from presto_tpu.exec.runtime import ExecContext, run_plan
    from presto_tpu.obs import runstats

    monkeypatch.setenv("PRESTO_TPU_CACHE_DIR", str(tmp_path))
    runstats.reset()
    try:
        n = 20_000
        cat = Catalog()
        conn = MemoryConnector()
        conn.add_table("bl", pd.DataFrame({
            "k": rng.integers(0, 5_000, n).astype(np.int64),
            "w": rng.normal(size=n)}))
        conn.add_table("pr", pd.DataFrame({
            "j": rng.integers(0, 5_000, 8_000).astype(np.int64),
            "v": rng.normal(size=8_000)}))
        cat.register("m", conn, default=True)
        sql = "select pr.v, bl.w from pr join bl on pr.j = bl.k"

        def _run(hbo):
            r = LocalRunner(cat, ExecConfig(
                batch_rows=1 << 13, memory_pool_bytes=96 << 10,
                spill_partitions=2, spill_max_depth=3, hbo=hbo))
            qp = r.plan(sql)
            ctx = ExecContext(cat, r.config)
            out = run_plan(qp, ctx).to_pandas()
            return out, ctx.stats.get("spill.repartitions", 0)

        got1, waves1 = _run("observe")
        assert waves1 > 0, "first run should pay repartition waves"
        got2, waves2 = _run("correct")
        assert waves2 < waves1, (
            f"hbo=correct run paid {waves2} repartition waves, "
            f"first run paid {waves1}")
        assert_frames_match(got2, got1.copy(), sort_by=["v", "w"])
    finally:
        runstats.reset()
