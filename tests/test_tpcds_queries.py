"""TPC-DS query shapes over the full 24-table connector, verified against
sqlite3 (Q3/Q7/Q19/Q42-style star joins + cross-channel and inventory
shapes). Queries are the spec's join/aggregation skeletons over the
generator's columns."""

import sqlite3

import numpy as np
import pandas as pd
import pytest

from presto_tpu.catalog.tpcds import TpcdsConnector, tpcds_catalog
from presto_tpu.exec import ExecConfig, LocalRunner


@pytest.fixture(scope="module")
def engines():
    cat = tpcds_catalog(0.01)
    runner = LocalRunner(cat, ExecConfig(batch_rows=1 << 15,
                                         agg_capacity=1 << 14))
    conn: TpcdsConnector = cat.connectors["tpcds"]
    db = sqlite3.connect(":memory:")
    for t in ("date_dim", "item", "store", "store_sales", "catalog_sales",
              "web_sales", "web_site", "promotion", "warehouse",
              "inventory", "customer_demographics"):
        conn._ensure(t)
        mt = conn.tables[t]
        df = pd.DataFrame({
            c: (mt.dicts[c].decode(mt.arrays[c]) if c in mt.dicts
                else mt.arrays[c])
            for c in mt.arrays
        })
        # decimals are stored as scaled ints; give sqlite the same ints
        df.to_sql(t, db, index=False)
    return runner, db


def _compare(runner, db, engine_sql, sqlite_sql=None, rtol=1e-9):
    got = runner.run(engine_sql)
    exp = pd.read_sql_query(sqlite_sql or engine_sql, db)
    assert list(got.columns) == list(exp.columns)
    assert len(got) == len(exp), (len(got), len(exp))
    for c in got.columns:
        g, e = got[c], exp[c]
        try:
            gf, ef = g.astype(float), e.astype(float)
        except (TypeError, ValueError):
            assert g.tolist() == e.tolist(), c
            continue
        np.testing.assert_allclose(gf, ef, rtol=rtol, err_msg=c)


def test_q3_shape_brand_by_year(engines):
    """Q3: store_sales x date_dim x item, brand rollup."""
    runner, db = engines
    sql = ("select d.d_year, i.i_brand_id, sum(ss.ss_ext_sales_price) as s "
           "from store_sales ss "
           "join date_dim d on ss.ss_sold_date_sk = d.d_date_sk "
           "join item i on ss.ss_item_sk = i.i_item_sk "
           "where i.i_manufact_id = 100 and d.d_moy = 11 "
           "group by d.d_year, i.i_brand_id "
           "order by d.d_year, s desc, i.i_brand_id limit 20")
    # engine decimals are exact DECIMAL; sqlite got raw scaled ints
    _compare(runner, db, sql,
             sqlite_sql=sql.replace("sum(ss.ss_ext_sales_price)",
                                    "sum(ss.ss_ext_sales_price) / 100.0"))


def test_q7_shape_demographics_filter(engines):
    """Q7: star join through customer_demographics + promotion."""
    runner, db = engines
    sql = ("select i.i_item_id, avg(ss.ss_quantity) as agg1, "
           "count(*) as n "
           "from store_sales ss "
           "join customer_demographics cd on ss.ss_cdemo_sk = cd.cd_demo_sk "
           "join promotion p on ss.ss_promo_sk = p.p_promo_sk "
           "join item i on ss.ss_item_sk = i.i_item_sk "
           "where cd.cd_gender = 'M' and cd.cd_marital_status = 'S' "
           "and p.p_channel_email = 'N' "
           "group by i.i_item_id order by i.i_item_id limit 50")
    _compare(runner, db, sql)


def test_q42_shape_category_by_year(engines):
    """Q42: category rollup for one month."""
    runner, db = engines
    sql = ("select d.d_year, i.i_category_id, i.i_category, "
           "sum(ss.ss_ext_sales_price) as s from store_sales ss "
           "join date_dim d on ss.ss_sold_date_sk = d.d_date_sk "
           "join item i on ss.ss_item_sk = i.i_item_sk "
           "where i.i_manufact_id < 200 and d.d_moy = 12 and d.d_year = 2000 "
           "group by d.d_year, i.i_category_id, i.i_category "
           "order by s desc, d.d_year, i.i_category_id, i.i_category "
           "limit 10")
    _compare(runner, db, sql,
             sqlite_sql=sql.replace("sum(ss.ss_ext_sales_price)",
                                    "sum(ss.ss_ext_sales_price) / 100.0"))


def test_cross_channel_union(engines):
    """Q71-style: all three channels unioned then rolled up by item."""
    runner, db = engines
    sql = ("select i.i_brand_id, sum(u.price) as s, count(*) as n from ("
           "select ss_item_sk as item_sk, ss_ext_sales_price as price "
           "from store_sales "
           "union all "
           "select cs_item_sk as item_sk, cs_ext_sales_price as price "
           "from catalog_sales "
           "union all "
           "select ws_item_sk as item_sk, ws_ext_sales_price as price "
           "from web_sales) u "
           "join item i on u.item_sk = i.i_item_sk "
           "where i.i_manufact_id = 5 "
           "group by i.i_brand_id order by i.i_brand_id")
    _compare(runner, db, sql,
             sqlite_sql=sql.replace("sum(u.price)", "sum(u.price) / 100.0"))


def test_q22_shape_inventory_rollup(engines):
    """Q22: inventory average quantity on hand by item."""
    runner, db = engines
    sql = ("select i.i_product_name, avg(inv.inv_quantity_on_hand) as qoh "
           "from inventory inv "
           "join date_dim d on inv.inv_date_sk = d.d_date_sk "
           "join item i on inv.inv_item_sk = i.i_item_sk "
           "where d.d_year = 2000 "
           "group by i.i_product_name "
           "order by qoh, i.i_product_name limit 25")
    _compare(runner, db, sql)


def test_web_channel_site_rollup(engines):
    runner, db = engines
    sql = ("select w.web_name, count(*) as n, "
           "sum(ws.ws_net_profit) as profit from web_sales ws "
           "join web_site w on ws.ws_web_site_sk = w.web_site_sk "
           "join date_dim d on ws.ws_sold_date_sk = d.d_date_sk "
           "where d.d_year = 2001 "
           "group by w.web_name order by w.web_name")
    _compare(runner, db, sql,
             sqlite_sql=sql.replace("sum(ws.ws_net_profit)",
                                    "sum(ws.ws_net_profit) / 100.0"))
