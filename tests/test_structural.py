"""Structural types (ARRAY / MAP) + UNNEST + array functions.

Reference surface: presto-spi/.../type/ArrayType.java, MapType.java,
operator/unnest/UnnestOperator.java, operator/scalar Array*/Map* functions,
operator/aggregation/ArrayAggregationFunction. Oracles are hand-computed
python values (sqlite has no arrays)."""

import numpy as np
import pandas as pd
import pytest

from presto_tpu.catalog.memory import MemoryConnector
from presto_tpu.connector import Catalog
from presto_tpu.exec import ExecConfig, LocalRunner
from presto_tpu.types import ArrayType, BIGINT, MapType, RowType, VARCHAR, parse_type


@pytest.fixture(scope="module")
def runner():
    conn = MemoryConnector()
    conn.add_table("t", {
        "id": np.array([1, 2, 3, 4]),
        "arr": [[1, 2, 3], [4, 5], [], [7, None, 9]],
        "tags": [["a", "b"], ["b"], ["c", "a"], []],
        "m": [{"x": 1.5, "y": 2.5}, {"x": 10.0}, {}, {"z": 7.0, "x": None}],
    })
    conn.add_table("s", {
        "id": np.array([1, 2, 3, 4]),
        "name": np.array(["one", "two", "three", "four"]),
    })
    cat = Catalog()
    cat.register("memory", conn, default=True)
    return LocalRunner(cat, ExecConfig())


def rows(runner, sql):
    return runner.run(sql)  # LocalRunner.run returns a DataFrame


class TestTypeParsing:
    def test_parse(self):
        t = parse_type("array(bigint)")
        assert isinstance(t, ArrayType) and t.element is BIGINT
        m = parse_type("map(varchar, array(bigint))")
        assert isinstance(m, MapType) and isinstance(m.value, ArrayType)
        r = parse_type("row(a bigint, b varchar)")
        assert isinstance(r, RowType)
        assert r.field_type("b") is VARCHAR


class TestArrayExpressions:
    def test_ctor_and_cardinality(self, runner):
        df = rows(runner, "select array[1,2,3] as a, "
                          "cardinality(array[1,2,3]) as c")
        assert df["a"][0] == [1, 2, 3]
        assert df["c"][0] == 3

    def test_subscript(self, runner):
        df = rows(runner, "select array[10,20,30][2] as x")
        assert df["x"][0] == 20

    def test_element_at_negative(self, runner):
        df = rows(runner, "select element_at(array[10,20,30], -1) as x, "
                          "element_at(array[10,20,30], 9) as y")
        assert df["x"][0] == 30
        assert df["y"][0] is None or pd.isna(df["y"][0])

    def test_table_arrays(self, runner):
        df = rows(runner, "select id, cardinality(arr) as c, arr[1] as h "
                          "from t order by id")
        assert list(df["c"]) == [3, 2, 0, 3]
        assert df["h"][0] == 1 and df["h"][1] == 4
        assert df["h"][2] is None or pd.isna(df["h"][2])

    def test_contains_position(self, runner):
        df = rows(runner, "select id, contains(arr, 5) as c5, "
                          "array_position(arr, 5) as p5 from t order by id")
        # row 4 ([7, NULL, 9]): not-found over an array WITH a NULL element
        # is unknown → NULL, not FALSE/0 (Presto three-valued semantics)
        assert list(df["c5"][:3]) == [False, True, False]
        assert df["c5"][3] is None or pd.isna(df["c5"][3])
        assert list(df["p5"][:3]) == [0, 2, 0]
        assert df["p5"][3] is None or pd.isna(df["p5"][3])

    def test_contains_found_with_null_element(self, runner):
        # a HIT is still TRUE/position even when the array has NULLs
        df = rows(runner, "select contains(arr, 7) as c7, "
                          "array_position(arr, 9) as p9 from t where id = 4")
        assert bool(df["c7"][0]) is True
        assert df["p9"][0] == 3

    def test_string_arrays(self, runner):
        df = rows(runner, "select id, contains(tags, 'a') as ha, tags "
                          "from t order by id")
        assert list(df["ha"]) == [True, False, True, False]
        assert df["tags"][0] == ["a", "b"]

    def test_min_max_sum_avg(self, runner):
        df = rows(runner, "select array_min(array[3,1,2]) as mn, "
                          "array_max(array[3,1,2]) as mx, "
                          "array_sum(array[3,1,2]) as s, "
                          "array_average(array[3,1,3]) as av")
        assert df["mn"][0] == 1 and df["mx"][0] == 3
        assert df["s"][0] == 6
        assert abs(df["av"][0] - 7 / 3) < 1e-12

    def test_min_with_null_element(self, runner):
        # arrays containing NULL yield NULL (ArrayMinMaxUtils semantics)
        df = rows(runner, "select id, array_min(arr) as mn from t order by id")
        assert df["mn"][0] == 1
        assert df["mn"][3] is None or pd.isna(df["mn"][3])

    def test_concat_slice(self, runner):
        df = rows(runner, "select array[1,2] || array[3] as c, "
                          "slice(array[1,2,3,4], 2, 2) as s")
        assert df["c"][0] == [1, 2, 3]
        assert df["s"][0] == [2, 3]

    def test_distinct_sort(self, runner):
        df = rows(runner, "select array_distinct(array[3,1,3,2,1]) as d, "
                          "array_sort(array[3,1,2]) as s")
        assert df["d"][0] == [1, 2, 3]
        assert df["s"][0] == [1, 2, 3]

    def test_sequence_repeat(self, runner):
        df = rows(runner, "select sequence(2, 6, 2) as s, repeat(7, 3) as r")
        assert df["s"][0] == [2, 4, 6]
        assert df["r"][0] == [7, 7, 7]


class TestMapExpressions:
    def test_map_ctor_element_at(self, runner):
        df = rows(runner,
                  "select element_at(map(array['a','b'], array[1.5,2.5]), "
                  "'b') as v")
        assert df["v"][0] == 2.5

    def test_table_map(self, runner):
        df = rows(runner, "select id, cardinality(m) as c, "
                          "element_at(m, 'x') as x from t order by id")
        assert list(df["c"]) == [2, 1, 0, 2]
        assert df["x"][0] == 1.5 and df["x"][1] == 10.0
        assert df["x"][2] is None or pd.isna(df["x"][2])
        # x is present-but-NULL in row 4
        assert df["x"][3] is None or pd.isna(df["x"][3])

    def test_map_keys_values(self, runner):
        df = rows(runner, "select map_keys(m) as mk, map_values(m) as mv "
                          "from t where id = 1")
        assert sorted(df["mk"][0]) == ["x", "y"]
        assert sorted(df["mv"][0]) == [1.5, 2.5]


class TestUnnest:
    def test_constant_unnest(self, runner):
        df = rows(runner, "select x from unnest(array[10,20,30]) as u(x)")
        assert list(df["x"]) == [10, 20, 30]

    def test_with_ordinality(self, runner):
        df = rows(runner, "select x, o from "
                          "unnest(array[7,8]) with ordinality as u(x, o)")
        assert list(df["x"]) == [7, 8]
        assert list(df["o"]) == [1, 2]

    def test_lateral_cross_join(self, runner):
        df = rows(runner, "select id, e from t cross join unnest(arr) "
                          "as u(e) order by id, e")
        # id 3 has an empty array → no rows; NULL element of id 4 kept
        got = [(int(i), e) for i, e in zip(df["id"], df["e"])]
        assert (1, 1) in got and (2, 5) in got
        assert not any(i == 3 for i, _ in got)
        assert len(got) == 3 + 2 + 3

    def test_unnest_map(self, runner):
        df = rows(runner, "select id, k, v from t cross join unnest(m) "
                          "as u(k, v) where id = 1 order by k")
        assert list(df["k"]) == ["x", "y"]
        assert list(df["v"]) == [1.5, 2.5]

    def test_unnest_join_downstream(self, runner):
        # UNNEST feeding a hash join (element joins a dimension table)
        df = rows(runner,
                  "select s.name, count(*) as n "
                  "from t cross join unnest(arr) as u(e) "
                  "join s on u.e = s.id group by s.name order by s.name")
        # elements: [1,2,3],[4,5],[],[7,None,9] → ids 1..4 present: 1,2,3,4
        got = dict(zip(df["name"], df["n"]))
        assert got == {"one": 1, "two": 1, "three": 1, "four": 1}

    def test_unnest_aggregate(self, runner):
        df = rows(runner, "select sum(e) as s from t "
                          "cross join unnest(arr) as u(e)")
        assert df["s"][0] == 1 + 2 + 3 + 4 + 5 + 7 + 9


class TestArrayAgg:
    def test_global(self, runner):
        df = rows(runner, "select array_agg(id) as a from t")
        assert sorted(df["a"][0]) == [1, 2, 3, 4]

    def test_grouped(self, runner):
        conn = MemoryConnector()
        conn.add_table("g", {
            "k": np.array(["a", "a", "b", "b", "b"]),
            "v": np.array([1, 2, 3, 4, 5]),
        })
        cat = Catalog()
        cat.register("memory", conn, default=True)
        r = LocalRunner(cat, ExecConfig())
        df = r.run("select k, array_agg(v) as vs, count(*) as n "
                   "from g group by k order by k")
        assert sorted(df["vs"][0]) == [1, 2]
        assert sorted(df["vs"][1]) == [3, 4, 5]
        assert list(df["n"]) == [2, 3]

    def test_cardinality_of_array_agg(self, runner):
        df = rows(runner, "select cardinality(array_agg(id)) as c from t")
        assert df["c"][0] == 4


class TestStructuralThroughOperators:
    def test_array_through_join(self, runner):
        # structural planes must survive the join gather (the Column.hi
        # regression class from round 2, now for sizes/evalid/keys)
        df = rows(runner,
                  "select s.name, t.arr from t join s on t.id = s.id "
                  "where s.id = 2")
        assert df["arr"][0] == [4, 5]

    def test_array_through_sort_limit(self, runner):
        df = rows(runner, "select id, arr from t order by id desc limit 2")
        assert list(df["id"]) == [4, 3]
        assert df["arr"][1] == []

    def test_map_through_filter(self, runner):
        df = rows(runner, "select m from t where element_at(m, 'x') > 2")
        assert df["m"][0] == {"x": 10.0}


class TestReviewRegressions:
    """Pinned fixes from the structural-types code review."""

    def test_ctas_array_roundtrip(self, runner):
        # _batches_to_host must carry structural planes into CTAS
        runner.run("drop table if exists ctas_arr")
        runner.run("create table ctas_arr as "
                   "select id, arr, tags, m from t")
        df = rows(runner, "select id, arr, tags, cardinality(m) as cm "
                          "from ctas_arr order by id")
        assert df["arr"][0] == [1, 2, 3]
        assert df["tags"][2] == ["c", "a"]
        assert list(df["cm"]) == [2, 1, 0, 2]
        runner.run("drop table ctas_arr")

    def test_ctas_array_agg_roundtrip(self, runner):
        runner.run("drop table if exists ctas_agg")
        runner.run("create table ctas_agg as "
                   "select array_agg(id) as ids from t")
        df = rows(runner, "select cardinality(ids) as c from ctas_agg")
        assert df["c"][0] == 4
        runner.run("drop table ctas_agg")

    def test_map_cardinality_mismatch_yields_null(self, runner):
        # keys beyond the value cardinality -> NULL value, not garbage
        df = rows(runner, "select element_at(map(array[1,2], array[9]), 2) "
                          "as v, element_at(map(array[1,2], array[9]), 1) "
                          "as w")
        assert df["v"][0] is None or pd.isna(df["v"][0])
        assert df["w"][0] == 9

    def test_array_literal_not_in_column_dict(self, runner):
        # literal absent from the column dictionary must keep its value
        df = rows(runner,
                  "select array['zzz_total', name][1] as x, "
                  "array['zzz_total', name][2] as y from s where id = 1")
        assert df["x"][0] == "zzz_total"
        assert df["y"][0] == "one"

    def test_slice_negative_out_of_range_empty(self, runner):
        df = rows(runner, "select slice(array[1,2,3], -4, 3) as a, "
                          "slice(array[1,2,3], -2, 2) as b")
        assert df["a"][0] == []
        assert df["b"][0] == [2, 3]


class TestGuards:
    def test_array_comparison_rejected(self, runner):
        from presto_tpu.plan.builder import AnalysisError

        with pytest.raises(AnalysisError):
            runner.run("select * from t where arr = arr")

    def test_group_by_array_rejected(self, runner):
        from presto_tpu.plan.builder import AnalysisError

        with pytest.raises(AnalysisError):
            runner.run("select count(*) from t group by arr")


class TestLambdas:
    """Higher-order array functions: the lambda body vectorizes over the
    flattened element plane (LambdaDefinitionExpression redesigned —
    no per-element interpretation)."""

    def test_transform(self, runner):
        df = rows(runner, "select transform(array[1,2,3], x -> x * 10) as a")
        assert df["a"][0] == [10, 20, 30]

    def test_transform_captures_outer_column(self, runner):
        df = rows(runner, "select id, transform(arr, x -> x + id) as a "
                          "from t where id = 2")
        assert df["a"][0] == [6, 7]

    def test_transform_null_elements(self, runner):
        df = rows(runner, "select transform(arr, x -> coalesce(x, 0)) as a "
                          "from t where id = 4")
        assert df["a"][0] == [7, 0, 9]

    def test_transform_string_body(self, runner):
        df = rows(runner, "select transform(tags, x -> upper(x)) as a "
                          "from t where id = 1")
        assert df["a"][0] == ["A", "B"]

    def test_filter(self, runner):
        df = rows(runner, "select filter(array[5,1,8,2], x -> x > 3) as a")
        assert df["a"][0] == [5, 8]

    def test_filter_keeps_order_and_sizes(self, runner):
        df = rows(runner, "select id, cardinality(filter(arr, x -> x > 2)) "
                          "as c from t order by id")
        assert list(df["c"]) == [1, 2, 0, 2]  # NULL element not > 2

    def test_reduce(self, runner):
        df = rows(runner,
                  "select reduce(array[1,2,3,4], 0, (s, x) -> s + x) as s, "
                  "reduce(array[2,3], 1, (s, x) -> s * x) as p")
        assert df["s"][0] == 10
        assert df["p"][0] == 6

    def test_match_functions(self, runner):
        df = rows(runner,
                  "select any_match(array[1,2,3], x -> x > 2) as a, "
                  "all_match(array[1,2,3], x -> x > 0) as b, "
                  "none_match(array[1,2,3], x -> x > 9) as c, "
                  "any_match(array[1,2,3], x -> x > 9) as d")
        assert bool(df["a"][0]) and bool(df["b"][0]) and bool(df["c"][0])
        assert not bool(df["d"][0])

    def test_lambda_param_shadows_column(self, runner):
        # `id` as a lambda param must shadow the table column
        df = rows(runner, "select transform(arr, id -> id * 0) as a "
                          "from t where id = 1")
        assert df["a"][0] == [0, 0, 0]

    def test_nested_higher_order(self, runner):
        df = rows(runner,
                  "select reduce(filter(arr, x -> x is not null), 0, "
                  "(s, x) -> s + x) as s from t order by id")
        assert list(df["s"]) == [6, 9, 0, 16]


class TestMapLambdas:
    def test_transform_values(self, runner):
        df = rows(runner,
                  "select transform_values(map(array['a','b'], "
                  "array[1.0, 2.0]), (k, v) -> v * 10) as m")
        assert df["m"][0] == {"a": 10.0, "b": 20.0}

    def test_map_filter(self, runner):
        df = rows(runner,
                  "select map_filter(map(array['a','b','c'], "
                  "array[1, 2, 3]), (k, v) -> v > 1) as m")
        assert df["m"][0] == {"b": 2, "c": 3}

    def test_map_filter_on_key(self, runner):
        df = rows(runner,
                  "select map_filter(m, (k, v) -> k = 'x') as mm "
                  "from t where id = 1")
        assert df["mm"][0] == {"x": 1.5}

    def test_transform_values_on_table_map(self, runner):
        df = rows(runner,
                  "select id, transform_values(m, (k, v) -> v + id) as mm "
                  "from t where id = 2")
        assert df["mm"][0] == {"x": 12.0}


class TestArraySetFunctions:
    def test_union_intersect_except(self, runner):
        df = rows(runner,
                  "select array_union(array[1,2,2], array[2,3]) as u, "
                  "array_intersect(array[1,2,3], array[2,3,4]) as i, "
                  "array_except(array[1,2,3], array[2]) as e, "
                  "arrays_overlap(array[1,2], array[2,9]) as o1, "
                  "arrays_overlap(array[1,2], array[8,9]) as o2")
        assert df.u[0] == [1, 2, 3]
        assert df.i[0] == [2, 3]
        assert df.e[0] == [1, 3]
        assert bool(df.o1[0]) and not bool(df.o2[0])

    def test_string_array_set_ops_cross_dictionary(self, runner):
        # tags column dict vs literal-ctor dict: codes must align
        df = rows(runner,
                  "select id, array_intersect(tags, array['a', 'zzz']) as i "
                  "from t order by id")
        assert df.i[0] == ["a"] and df.i[1] == [] and df.i[2] == ["a"]

    def test_map_concat(self, runner):
        df = rows(runner,
                  "select map_concat(map(array['a','b'], array[1,2]), "
                  "map(array['b','c'], array[20,30])) as m")
        assert df.m[0] == {"a": 1, "b": 20, "c": 30}  # right side wins

    def test_map_agg(self, runner):
        df = rows(runner,
                  "select map_agg(name, id) as m from s")
        assert df.m[0] == {"one": 1, "two": 2, "three": 3, "four": 4}

    def test_map_agg_grouped(self, runner):
        conn = MemoryConnector()
        conn.add_table("kv", {
            "g": np.array([0, 0, 1, 1, 1]),
            "k": np.array(["x", "y", "x", "z", "x"]),
            "v": np.array([1.0, 2.0, 3.0, 4.0, 5.0]),
        })
        cat = Catalog()
        cat.register("m", conn, default=True)
        r = LocalRunner(cat, ExecConfig())
        df = r.run("select g, map_agg(k, v) as m from kv group by g "
                   "order by g")
        assert df.m[0] == {"x": 1.0, "y": 2.0}
        # duplicate key 'x' in group 1: first occurrence wins
        assert df.m[1] == {"x": 3.0, "z": 4.0}


class TestZipWith:
    def test_zip_with(self, runner):
        df = rows(runner,
                  "select zip_with(array[1,2,3], array[10,20,30], "
                  "(x, y) -> x + y) as z")
        assert df.z[0] == [11, 22, 33]

    def test_zip_with_uneven_pads_null(self, runner):
        df = rows(runner,
                  "select zip_with(array[1,2,3], array[10], "
                  "(x, y) -> coalesce(y, 0) + x) as z")
        assert df.z[0] == [11, 2, 3]

    def test_zip_with_table_columns(self, runner):
        df = rows(runner,
                  "select id, zip_with(arr, arr, (x, y) -> x * y) as sq "
                  "from t where id = 2")
        assert df.sq[0] == [16, 25]
