"""Serving-plane SLO telemetry: lifecycle timelines, live progress,
objectives/regression counters, and the unified cluster event stream
(obs/lifecycle.py + obs/events.py + the querymanager EXPIRED state)."""

import json
import threading
import time

import pytest

from presto_tpu.obs import events as obs_events
from presto_tpu.obs import lifecycle
from presto_tpu.obs import runstats
from presto_tpu.server.querymanager import (
    EXPIRED,
    FINISHED,
    QueryManager,
    QueryResult,
)
from presto_tpu.server.session import Session, SessionPropertyError


@pytest.fixture(autouse=True)
def _clean_plane():
    lifecycle.reset()
    obs_events.EVENTS.clear()
    runstats.reset()
    yield
    lifecycle.reset()
    obs_events.EVENTS.clear()
    runstats.reset()


def _sum_segments(segs):
    return sum(v for k, v in segs.items() if k != "e2e")


# ---------------------------------------------------------------------------
# timeline segment math


def test_timeline_full_walk_sums_to_e2e():
    tl = lifecycle.Timeline(created=100.0)
    tl.mark("queued", 100.5)
    tl.mark("admitted", 101.0)
    tl.mark("planning", 101.0)
    tl.mark("compiling", 101.25)
    tl.mark("executing", 102.0)
    tl.mark("draining", 103.5)
    tl.finish("finished", 103.75)
    segs = tl.segments()
    assert segs["queue_wait"] == pytest.approx(1.0)
    assert segs["plan"] == pytest.approx(0.25)
    assert segs["compile"] == pytest.approx(0.75)
    assert segs["exec"] == pytest.approx(1.5)
    assert segs["drain"] == pytest.approx(0.25)
    assert segs["e2e"] == pytest.approx(3.75)
    assert _sum_segments(segs) == pytest.approx(segs["e2e"])


def test_timeline_missing_marks_resolve_right():
    # a query that dies while queued books its whole life to queue_wait
    tl = lifecycle.Timeline(created=10.0)
    tl.finish("canceled", 12.0)
    segs = tl.segments()
    assert segs["queue_wait"] == pytest.approx(2.0)
    assert segs["plan"] == segs["compile"] == segs["exec"] == segs["drain"] == 0.0
    assert segs["e2e"] == pytest.approx(2.0)

    # coordinator-side statement: only planning was stamped, everything
    # after books to the plan segment
    tl = lifecycle.Timeline(created=10.0)
    tl.mark("planning", 10.5)
    tl.finish("finished", 11.5)
    segs = tl.segments()
    assert segs["queue_wait"] == pytest.approx(0.5)
    assert segs["plan"] == pytest.approx(1.0)
    assert _sum_segments(segs) == pytest.approx(segs["e2e"])


def test_timeline_first_mark_wins_and_terminal_absorbs():
    tl = lifecycle.Timeline(created=1.0)
    assert tl.mark("executing", 2.0)
    assert not tl.mark("executing", 5.0)  # replay re-entry: first wins
    assert tl.finish("finished", 3.0)
    assert not tl.finish("failed", 4.0)
    assert not tl.mark("draining", 3.5)  # late mark after terminal dropped
    assert tl.terminal == "finished"
    assert tl.marks["executing"] == 2.0


def test_timeline_running_query_segments_track_now():
    tl = lifecycle.Timeline(created=50.0)
    tl.mark("planning", 51.0)
    segs = tl.segments(now=53.0)
    assert segs["e2e"] == pytest.approx(3.0)
    assert _sum_segments(segs) == pytest.approx(3.0)


# ---------------------------------------------------------------------------
# objectives parsing


def test_parse_objectives():
    out = lifecycle.parse_objectives("e2e=1.5, queue_wait=0.25")
    assert out == {"e2e": 1.5, "queue_wait": 0.25}
    assert lifecycle.parse_objectives("") == {}
    with pytest.raises(ValueError):
        lifecycle.parse_objectives("warp_speed=1")
    with pytest.raises(ValueError):
        lifecycle.parse_objectives("e2e=0")
    with pytest.raises(ValueError):
        lifecycle.parse_objectives("e2e")
    with pytest.raises(ValueError):
        lifecycle.parse_objectives("e2e=fast")


def test_slo_objectives_session_property_validation():
    s = Session()
    s.set("slo_objectives", "e2e=2.0,exec=1.0")
    with pytest.raises(SessionPropertyError):
        s.set("slo_objectives", "bogus_segment=1")


# ---------------------------------------------------------------------------
# cluster event stream


def test_event_stream_ring_and_filters(tmp_path):
    es = obs_events.ClusterEventStream(capacity=4)
    for i in range(6):
        es.emit("lifecycle", query_id=f"q{i % 2}", state="created")
    evs = es.events()
    assert len(evs) == 4  # bounded ring
    seqs = [e["seq"] for e in evs]
    assert seqs == sorted(seqs) and seqs[-1] == 6
    only_q1 = es.events(query_id="q1")
    assert all(e["queryId"] == "q1" for e in only_q1)
    assert all(e["traceToken"] == "q1" for e in only_q1)
    assert es.events(since=5) == [e for e in evs if e["seq"] > 5]
    assert es.events(kind="nope") == []

    sink = tmp_path / "events.jsonl"
    es.configure(path=str(sink))
    es.emit("slo_violation", query_id="qx", segment="e2e")
    recs = [json.loads(l) for l in sink.read_text().splitlines()]
    assert recs[-1]["kind"] == "slo_violation"
    assert recs[-1]["traceToken"] == "qx"


def test_event_stream_concurrent_writers_paged_reads_no_gaps():
    # N writer threads publish while readers page with since=; every
    # reader must observe every seq exactly once, in order — the emit
    # critical section assigns seq and appends atomically, and events()
    # pages oldest-first so a full page never skips what the ring holds
    es = obs_events.ClusterEventStream(capacity=10000)
    n_writers, per = 6, 150
    total = n_writers * per
    done = threading.Event()

    def writer(i):
        for j in range(per):
            es.emit("even" if j % 2 == 0 else "odd",
                    query_id=f"w{i}", n=j)

    collected = {}

    def reader(name):
        seqs = []
        since = 0
        while True:
            page = es.events(since=since, limit=37)
            if page:
                seqs.extend(e["seq"] for e in page)
                since = seqs[-1]
            elif done.is_set() and since >= es.last_seq():
                break
            else:
                time.sleep(0.001)
        collected[name] = seqs

    readers = [threading.Thread(target=reader, args=(f"r{k}",))
               for k in range(2)]
    writers = [threading.Thread(target=writer, args=(i,))
               for i in range(n_writers)]
    for t in readers:
        t.start()
    for t in writers:
        t.start()
    for t in writers:
        t.join()
    done.set()
    for t in readers:
        t.join()

    assert es.last_seq() == total
    for seqs in collected.values():
        assert seqs == list(range(1, total + 1))


def test_event_stream_limit_and_kind_filters_compose():
    es = obs_events.ClusterEventStream(capacity=10000)
    for i in range(60):
        es.emit("even" if i % 2 == 0 else "odd", query_id=f"q{i % 3}", n=i)
    # kind filter then limit: oldest `limit` of the matching events
    page = es.events(kind="even", limit=10)
    assert len(page) == 10
    assert all(e["kind"] == "even" for e in page)
    assert [e["seq"] for e in page] == list(range(1, 21, 2))
    # since + kind + limit page through the filtered stream without skips
    seen = []
    since = 0
    while True:
        page = es.events(since=since, kind="odd", limit=7)
        if not page:
            break
        seen.extend(e["seq"] for e in page)
        since = page[-1]["seq"]
    assert seen == list(range(2, 61, 2))
    # query_id composes with kind
    both = es.events(query_id="q0", kind="even")
    assert all(e["queryId"] == "q0" and e["kind"] == "even" for e in both)
    assert both  # q0, even: i % 3 == 0 and i % 2 == 0 both hold for i=0, 6, ...


def test_slow_query_logger_extra_annotation(tmp_path):
    from presto_tpu.server.querymanager import QueryInfo

    path = tmp_path / "slow.jsonl"
    logger = obs_events.SlowQueryLogger(str(path), threshold_s=0.0)
    now = time.time()
    info = QueryInfo(query_id="q1", sql="SELECT 1", state="FINISHED",
                     user="u", resource_group=None, create_time=now,
                     end_time=now + 0.5)
    logger.log(info, extra={"latencyRegression": {"factor": 3.0}})
    rec = json.loads(path.read_text().splitlines()[-1])
    assert rec["queryId"] == "q1"
    assert rec["latencyRegression"] == {"factor": 3.0}


# ---------------------------------------------------------------------------
# progress estimation


def test_progress_monotone_and_terminal():
    entry = lifecycle.register("q_prog")
    doc0 = lifecycle.progress_doc("q_prog")
    assert doc0["fraction"] == 0.0
    assert doc0["provenance"] == "fragments"

    # HBO prediction: 100 output rows expected
    runstats.note("fp_prog", lifecycle.HBO_SITE, rows=100.0, wall_s=10.0)
    lifecycle.set_fingerprint("q_prog", "fp_prog")
    assert entry.predicted["rows"] == 100.0

    lifecycle.observe_batch("q_prog", 50)
    d1 = lifecycle.progress_doc("q_prog")
    assert d1["provenance"] == "hbo"
    assert d1["fraction"] >= 0.5
    lifecycle.observe_batch("q_prog", 500)  # overshoot clamps below 1.0
    d2 = lifecycle.progress_doc("q_prog")
    assert d1["fraction"] <= d2["fraction"] <= 0.99

    entry.timeline.finish("finished")
    d3 = lifecycle.progress_doc("q_prog")
    assert d3["fraction"] == 1.0
    # running max: later polls never go backwards
    assert lifecycle.progress_doc("q_prog")["fraction"] == 1.0
    assert d3["predicted"]["rows"] == 100.0


def test_progress_fragments_fallback_and_worker_merge():
    lifecycle.register("q_frag")
    lifecycle.merge_worker_progress("w0", {
        "q_frag": {"rows": 10, "batches": 2, "tasksDone": 3, "tasksTotal": 4,
                   "fragmentsDone": 1, "fragmentsTotal": 2}})
    doc = lifecycle.progress_doc("q_frag")
    assert doc["provenance"] == "fragments"
    assert doc["fraction"] == pytest.approx(0.75)
    assert doc["workerRows"] == 10
    assert doc["fragments"] == {"done": 1, "total": 2}


def test_progress_alias_resolves_attempt_ids():
    lifecycle.register("q_serve")
    lifecycle.alias("attempt_1", "q_serve")
    lifecycle.merge_worker_progress("w0", {
        "attempt_1": {"rows": 7, "batches": 1, "tasksDone": 1,
                      "tasksTotal": 1, "fragmentsDone": 1,
                      "fragmentsTotal": 1}})
    assert lifecycle.progress_doc("q_serve")["workerRows"] == 7
    assert lifecycle.progress_doc("unknown") is None


# ---------------------------------------------------------------------------
# completion: histograms, objectives, regression


class _Info:
    def __init__(self, query_id, state="FINISHED"):
        self.query_id = query_id
        self.state = state


def test_complete_observes_histograms_and_violations():
    entry = lifecycle.register("q_slo", objectives={"e2e": 0.0001})
    entry.group = "global.batch"
    time.sleep(0.002)
    entry.timeline.finish("finished")
    lifecycle.complete(_Info("q_slo"))
    rows = lifecycle.metric_rows({"plane": "coordinator"})
    viol = [r for r in rows if r[0] == "presto_tpu_slo_violations_total"
            and r[3].get("group") == "global.batch"]
    assert viol and viol[0][2] == 1 and viol[0][3]["segment"] == "e2e"
    kinds = [e["kind"] for e in obs_events.EVENTS.events(query_id="q_slo")]
    assert "slo_violation" in kinds
    text = lifecycle.render_slo_histograms("coordinator")
    assert 'group="global.batch"' in text
    assert "presto_tpu_query_e2e_seconds_bucket" in text


def test_latency_regression_flags_and_records_profile():
    # baseline must exist BEFORE the run completes (note() max-merges)
    runstats.note("fp_reg", lifecycle.HBO_SITE, wall_s=0.0001)
    entry = lifecycle.register("q_reg", regression_factor=2.0)
    lifecycle.set_fingerprint("q_reg", "fp_reg")
    time.sleep(0.002)
    entry.timeline.finish("finished")
    lifecycle.complete(_Info("q_reg"))
    assert entry.regression is not None
    assert entry.regression["baselineWallS"] == pytest.approx(0.0001)
    assert lifecycle.slow_log_annotation("q_reg")["latencyRegression"][
        "fingerprint"] == "fp_reg"
    kinds = [e["kind"] for e in obs_events.EVENTS.events(query_id="q_reg")]
    assert "latency_regression" in kinds
    rows = lifecycle.metric_rows({})
    regr = [r for r in rows
            if r[0] == "presto_tpu_latency_regression_total" and r[2] > 0]
    assert regr
    # the completed profile was recorded back for the next run
    assert runstats.query_baseline("fp_reg")["wall_s"] > 0.0001


def test_no_regression_on_failed_queries():
    runstats.note("fp_f", lifecycle.HBO_SITE, wall_s=0.0001)
    entry = lifecycle.register("q_f", regression_factor=2.0)
    lifecycle.set_fingerprint("q_f", "fp_f")
    time.sleep(0.002)
    entry.timeline.finish("failed")
    lifecycle.complete(_Info("q_f", state="FAILED"))
    assert entry.regression is None


# ---------------------------------------------------------------------------
# querymanager integration: transitions, EXPIRED, lifecycle=off


def _instant(session, sql):
    return QueryResult(columns=["x"], types=["bigint"], rows=[(1,)])


def test_query_manager_emits_lifecycle_transitions():
    qm = QueryManager(execute_fn=_instant)
    try:
        s = Session(user="u")
        qe = qm.create_query(s, "SELECT 1")
        assert qe.wait(10)
        assert qe.state == FINISHED
        assert qe.timeline is not None
        states = [e["state"] for e in obs_events.EVENTS.events(
            query_id=qe.query_id, kind="lifecycle")]
        assert states[0] == "created"
        assert states[-1] == "finished"
        assert "admitted" in states and "planning" in states
        assert states.index("admitted") < states.index("planning")
        doc = qe.timeline.doc()
        assert doc["terminal"] == "finished"
        segs = doc["segments"]
        assert _sum_segments(segs) == pytest.approx(segs["e2e"], abs=1e-5)
        assert "lifecycle" in qe.info().stats
    finally:
        qm.close()


def test_query_manager_lifecycle_off_is_inert():
    qm = QueryManager(execute_fn=_instant)
    try:
        s = Session(user="u")
        s.set("lifecycle", "off")
        qe = qm.create_query(s, "SELECT 1")
        assert qe.wait(10)
        assert qe.timeline is None
        assert not lifecycle.armed()
        assert obs_events.EVENTS.events(query_id=qe.query_id) == []
        assert "lifecycle" not in qe.info().stats
    finally:
        qm.close()


def test_expired_is_distinct_terminal_state():
    stop = threading.Event()

    def _hang(session, sql):
        stop.wait(30)
        return QueryResult(columns=[], types=[], rows=[])

    qm = QueryManager(execute_fn=_hang)
    try:
        s = Session(user="u")
        s.set("query_max_run_time_s", 0.05)
        qe = qm.create_query(s, "SELECT slow()")
        assert qe.wait(15), "enforcement loop never expired the query"
        assert qe.state == EXPIRED
        assert "maximum run time of 0.05s" in qe.error
        assert "elapsed" in qe.error
        assert qe.error_type == "EXCEEDED_TIME_LIMIT"
        info = qe.info()
        assert info.stats["expired"]["limitS"] == 0.05
        assert info.stats["expired"]["elapsedS"] > 0
        states = [e["state"] for e in obs_events.EVENTS.events(
            query_id=qe.query_id, kind="lifecycle")]
        assert states[-1] == "expired"
        exp = [e for e in obs_events.EVENTS.events(query_id=qe.query_id)
               if e.get("state") == "expired"]
        assert exp[0]["limitS"] == 0.05
    finally:
        stop.set()
        qm.close()


def test_metric_rows_zeroed_when_armed_but_quiet():
    lifecycle.register("q_quiet")
    rows = lifecycle.metric_rows({"plane": "coordinator"})
    names = {r[0] for r in rows}
    assert names == {"presto_tpu_slo_violations_total",
                     "presto_tpu_latency_regression_total"}
    assert all(r[2] == 0 for r in rows)
    assert all(r[4] == "counter" for r in rows)
