"""Observability plane: span tracer + trace-token propagation across the
in-process cluster, histogram metric families, exposition-format lint,
and the slow-query event sink.

Reference modules: airlift trace-token propagation, DistributionStat /
TimeStat metrics export, the EventListener SPI's QueryCompletedEvent."""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pandas as pd
import pytest

from presto_tpu.catalog.memory import MemoryConnector
from presto_tpu.connector import Catalog
from presto_tpu.exec import ExecConfig
from presto_tpu.obs import metrics as obs_metrics
from presto_tpu.obs import trace as obs_trace
from presto_tpu.obs.events import SlowQueryLogger
from presto_tpu.obs.exposition import lint_exposition
from presto_tpu.server.metrics import _fmt, render_metrics


def _catalog():
    conn = MemoryConnector()
    rng = np.random.default_rng(7)
    conn.add_table("t", pd.DataFrame({"k": np.arange(400) % 7,
                                      "v": rng.normal(size=400)}))
    cat = Catalog()
    cat.register("m", conn, default=True)
    return cat


# -- metrics plane (unit) --------------------------------------------------


class TestHistograms:
    def test_log_buckets_shape(self):
        b = obs_metrics.log_buckets(0.01, 600.0)
        assert b == sorted(b)
        assert len(b) == len(set(b))
        assert b[0] == 0.01
        assert all(x > 0 for x in b)
        # last finite bound sits within one ratio step of hi (the +Inf
        # bucket covers the tail)
        assert b[-1] <= 600.0
        assert b[-1] >= 600.0 / (10.0 ** (1.0 / 3.0)) * 0.99

    def test_observe_render_and_plane_filter(self):
        h = obs_metrics.Histogram("test_obs_hist_seconds", "unit-test family",
                                  obs_metrics.log_buckets(0.001, 10.0))
        for v in (0.002, 0.002, 5.0):
            h.observe(v, plane="worker")
        h.observe(0.1, plane="coordinator")
        snap = h.snapshot("worker")
        assert len(snap) == 1
        (_, s), = snap.items()
        assert s["count"] == 3
        doc = "\n".join(h.render("worker")) + "\n"
        assert lint_exposition(doc) == []
        assert 'le="+Inf"' in doc
        assert "test_obs_hist_seconds_count" in doc
        # the coordinator observation never leaks into the worker plane
        assert 'plane="coordinator"' not in doc

    def test_empty_plane_renders_zeroed_family(self):
        h = obs_metrics.Histogram("test_obs_empty_seconds", "x",
                                  obs_metrics.log_buckets(0.001, 1.0))
        doc = "\n".join(h.render("worker")) + "\n"
        assert lint_exposition(doc) == []
        assert 'test_obs_empty_seconds_count{plane="worker"} 0' in doc

    def test_builtin_families_exist(self):
        names = {h.name for h in obs_metrics.ALL_HISTOGRAMS}
        assert len(names) >= 4
        doc = obs_metrics.render_histograms("coordinator")
        assert lint_exposition(doc) == []


class TestExpositionFormat:
    def test_label_escaping_roundtrip(self):
        line = _fmt("m", 1, {"q": 'a"b\\c\nd'})
        assert '\\"' in line and "\\\\" in line and "\\n" in line
        doc = "# HELP m x\n# TYPE m gauge\n" + line + "\n"
        assert lint_exposition(doc) == []

    def test_render_metrics_types_and_headers_once(self):
        doc = render_metrics([
            ("m_total", "monotone", 3, None),
            ("g", "by label", 1.5, {"a": "b"}),
            ("g", "by label", 2.5, {"a": "c"}),
            ("x", "explicit type wins", 7, None, "counter"),
        ])
        assert "# TYPE m_total counter" in doc
        assert "# TYPE g gauge" in doc
        assert doc.count("# TYPE g gauge") == 1
        assert doc.count("# HELP g") == 1
        assert "# TYPE x counter" in doc
        assert lint_exposition(doc) == []

    def test_lint_catches_duplicate_type(self):
        errs = lint_exposition("# TYPE m gauge\n# TYPE m gauge\nm 1\n")
        assert any("duplicate TYPE" in e for e in errs)

    def test_lint_catches_type_after_samples(self):
        errs = lint_exposition("# HELP m x\nm 1\n# TYPE m gauge\n")
        assert any("after its samples" in e for e in errs)

    def test_lint_catches_missing_type(self):
        errs = lint_exposition("m 1\n")
        assert any("no # TYPE" in e for e in errs)

    def test_lint_catches_bad_escape(self):
        errs = lint_exposition(
            '# HELP m x\n# TYPE m gauge\nm{a="b\\x"} 1\n')
        assert any("invalid escape" in e for e in errs)

    def test_lint_catches_histogram_defects(self):
        base = "# HELP h x\n# TYPE h histogram\n"
        errs = lint_exposition(
            base + 'h_bucket{le="1"} 1\nh_sum 1\nh_count 1\n')
        assert any("+Inf" in e for e in errs)
        errs = lint_exposition(
            base + 'h_bucket{le="1"} 5\nh_bucket{le="2"} 3\n'
            'h_bucket{le="+Inf"} 5\nh_sum 1\nh_count 5\n')
        assert any("monotone" in e for e in errs)
        errs = lint_exposition(
            base + 'h_bucket{le="+Inf"} 2\nh_sum 1\nh_count 3\n')
        assert any("_count" in e for e in errs)
        errs = lint_exposition(base + "h 1\n")
        assert any("invalid for histogram" in e for e in errs)

    def test_lint_catches_non_numeric_value(self):
        errs = lint_exposition("# HELP m x\n# TYPE m gauge\nm bogus\n")
        assert any("non-numeric" in e for e in errs)

    def test_cli(self, tmp_path):
        from presto_tpu.obs import exposition

        good = tmp_path / "good.prom"
        good.write_text("# HELP m x\n# TYPE m gauge\nm 1\n")
        assert exposition.main([str(good)]) == 0
        bad = tmp_path / "bad.prom"
        bad.write_text("m 1\n")
        assert exposition.main([str(bad)]) == 1


# -- tracer (unit) ---------------------------------------------------------


class TestTracer:
    def test_span_nesting_and_record_parenting(self):
        tr = obs_trace.Tracer()
        with tr.span("query", "query") as root:
            assert tr.root_id == root.span_id
            with tr.span("child", "operator") as ch:
                assert ch.parent_id == root.span_id
                sp = tr.record("compile", "compile", 1.0, 2.0)
                assert sp.parent_id == ch.span_id
        # spans append on close: inner-first
        assert [s.name for s in tr.spans()] == ["compile", "child", "query"]
        # off-stack records (producer threads) parent to the root
        late = tr.record("late", "operator", 1.0, 2.0)
        assert late.parent_id == tr.root_id

    def test_token_roundtrip(self):
        tr = obs_trace.Tracer(trace_id="t_x")
        with tr.span("query", "query") as root:
            tok = tr.token()
            assert obs_trace.parse_token(tok) == ("t_x", root.span_id)
        assert obs_trace.parse_token(
            obs_trace.format_token("t", None)) == ("t", None)

    def test_absorb_reparents_worker_dump(self):
        coord = obs_trace.Tracer(trace_id="T")
        with coord.span("query", "query"):
            stage = coord.record("stage-0", "stage", 0.0, 1.0)
        worker = obs_trace.Tracer(trace_id="T")
        with worker.span("task", "task"):
            worker.record("op", "operator", 0.0, 0.5)
        dump = worker.to_json()
        coord.absorb(dump["spans"], {dump["rootSpanId"]: stage.span_id})
        by_id = {s.span_id: s for s in coord.spans()}
        assert by_id[dump["rootSpanId"]].parent_id == stage.span_id
        tree = obs_trace.build_tree(coord.spans())
        assert len(tree) == 1  # one stitched root: the query span

    def test_max_spans_drops_and_counts(self):
        tr = obs_trace.Tracer(max_spans=2)
        for i in range(3):
            tr.record(f"s{i}", "operator", 0.0, 1.0)
        assert len(tr.spans()) == 2
        assert tr.dropped == 1
        assert tr.to_json()["dropped"] == 1

    def test_noop_tracer(self):
        n = obs_trace.NOOP
        assert n.enabled is False
        with n.span("a", "b") as sp:
            assert sp.duration_s == 0.0
        assert n.record("a", "b", 0, 1).span_id is None
        assert n.to_json()["spans"] == []
        assert n.token() == ""

    def test_thread_local_use(self):
        tr = obs_trace.Tracer()
        with obs_trace.use(tr):
            assert obs_trace.current() is tr
            with obs_trace.use(obs_trace.NOOP):
                assert obs_trace.current() is obs_trace.NOOP
            assert obs_trace.current() is tr
        assert obs_trace.current() is obs_trace.NOOP

    def test_registry_alias_get_latest_eviction(self):
        reg = obs_trace.TraceRegistry(max_traces=2)
        t1, t2, t3 = (obs_trace.Tracer() for _ in range(3))
        reg.register(t1, "a1")
        reg.register(t2)
        reg.register(t3)  # evicts t1 and its alias
        assert reg.get(t1.trace_id) is None
        assert reg.get("a1") is None
        assert reg.get(t2.trace_id) is t2
        assert reg.latest() is t3
        reg.alias("x", "never-registered")  # ignored, not an error
        assert reg.get("x") is None
        reg.alias("y", t3.trace_id)
        assert reg.get("y") is t3


# -- slow-query sink (unit) ------------------------------------------------


def _qinfo(qid="q1", elapsed=1.0):
    from presto_tpu.server.querymanager import QueryInfo

    now = 1000.0
    return QueryInfo(query_id=qid, sql="select 1", state="FINISHED",
                     user="u", resource_group=None, create_time=now,
                     end_time=now + elapsed)


def test_slow_query_logger_threshold_and_topk(tmp_path):
    p = str(tmp_path / "slow.jsonl")
    lg = SlowQueryLogger(p, threshold_s=0.5, top_k=2)
    lg.log(_qinfo(elapsed=0.1))  # below threshold: not logged
    spans = [obs_trace.Span(f"s{i}", None, f"op{i}", "operator",
                            0.0, float(i))
             for i in range(1, 5)]
    lg.log(_qinfo(qid="q2", elapsed=2.0), spans)
    with open(p) as fh:
        recs = [json.loads(line) for line in fh]
    assert len(recs) == 1
    rec = recs[0]
    assert rec["event"] == "queryCompleted"
    assert rec["queryId"] == "q2"
    assert rec["elapsedS"] == 2.0
    # top-k most expensive spans, most expensive first
    assert [t["name"] for t in rec["topSpans"]] == ["op4", "op3"]


# -- cluster integration ---------------------------------------------------


@pytest.fixture(scope="module")
def cluster():
    from presto_tpu.server.coordinator import DistributedRunner

    with DistributedRunner(_catalog(), n_workers=2) as dr:
        yield dr


class TestClusterTracing:
    def test_trace_token_propagation_and_stitching(self, cluster):
        coord = cluster.coordinator
        session = coord.protocol.session_from_headers({})
        qe = coord.query_manager.create_query(
            session, "select k, sum(v) as s from t group by k")
        assert qe.wait(60)
        assert qe.state == "FINISHED", qe.error
        with urllib.request.urlopen(
                f"{coord.url}/v1/query/{qe.query_id}/trace", timeout=10) as r:
            doc = json.loads(r.read())
        assert doc["traceId"] == qe.query_id
        spans = doc["spans"]
        by_kind = {}
        for s in spans:
            by_kind.setdefault(s["kind"], []).append(s)
        # worker task spans traveled back over the token header and got
        # stitched under synthesized stage spans under the query root
        assert "query" in by_kind and "stage" in by_kind \
            and "task" in by_kind
        root = next(s for s in spans if s["spanId"] == doc["rootSpanId"])
        assert root["name"] == "query"
        stage_ids = {s["spanId"] for s in by_kind["stage"]}
        for st in by_kind["stage"]:
            assert st["parentId"] == doc["rootSpanId"]
        for t in by_kind["task"]:
            assert t["parentId"] in stage_ids
            assert (t.get("attrs") or {}).get("node", "").startswith(
                "worker-")
        # the root span covers >= 95% of the whole trace envelope
        starts = [s["start"] for s in spans]
        ends = [s["end"] for s in spans if s["end"] is not None]
        envelope = max(ends) - min(starts)
        assert envelope >= 0.0
        assert root["durationS"] >= 0.95 * envelope
        # one nested tree rooted at the query span
        assert len(doc["tree"]) == 1
        assert doc["tree"][0]["spanId"] == doc["rootSpanId"]

    def test_statement_results_carry_trace_uri(self, cluster):
        req = urllib.request.Request(
            f"{cluster.coordinator.url}/v1/statement",
            data=b"select 1 as x", method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        assert "traceUri" in out
        assert "/trace" in out["traceUri"]

    def test_explain_analyze_compile_execute_split(self, cluster):
        out = cluster.coordinator.explain_analyze_distributed(
            "select k, avg(v) as a, max(v) as mx from t "
            "group by k having max(v) > -1e9")
        assert "-- task execution profile --" in out
        assert "wall=" in out
        # a first execution jit-compiles at least one node: the profile
        # splits per-operator wall into compile vs execute
        assert "compile=" in out and "execute=" in out

    def test_tracing_disabled_is_noop(self, cluster):
        import dataclasses as dc

        coord = cluster.coordinator
        before = coord.trace_registry.latest()
        cfg = dc.replace(cluster.config, tracing=False)
        coord.run_batch("select min(v) as x from t", cfg)
        assert coord.trace_registry.latest() is before

    def test_metrics_exposition_lint_both_planes(self, cluster):
        cluster.run("select count(*) as n from t")  # ensure observations
        urls = ([("coordinator", cluster.coordinator.url)]
                + [(w.node_id, w.url) for w in cluster.workers])
        for name, u in urls:
            with urllib.request.urlopen(f"{u}/v1/metrics", timeout=10) as r:
                body = r.read().decode()
            assert lint_exposition(body) == [], (name, lint_exposition(body))
            hist_fams = [line for line in body.splitlines()
                         if line.startswith("# TYPE")
                         and line.endswith(" histogram")]
            assert len(hist_fams) >= 4, name

    def test_ui_query_drilldown_page(self, cluster):
        coord = cluster.coordinator
        session = coord.protocol.session_from_headers({})
        qe = coord.query_manager.create_query(
            session, "select max(v) as mx from t")
        assert qe.wait(60)
        with urllib.request.urlopen(
                f"{coord.url}/ui/query/{qe.query_id}", timeout=10) as r:
            html = r.read().decode()
        assert qe.query_id in html
        assert "query" in html  # root span row renders
        # unknown query id 404s
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{coord.url}/ui/query/nope", timeout=10)
        assert ei.value.code == 404


def test_slow_query_log_end_to_end(tmp_path):
    from presto_tpu.server.coordinator import Coordinator
    from presto_tpu.server.worker import Worker

    log = str(tmp_path / "slow.jsonl")
    cat = _catalog()
    coord = Coordinator(cat, min_workers=1, slow_query_log=log)
    w = Worker(cat, node_id="w0", coordinator_url=coord.url)
    try:
        deadline = time.time() + 10
        while time.time() < deadline and not coord.node_manager.active_nodes():
            time.sleep(0.05)
        qe = coord.query_manager.create_query(
            coord.protocol.session_from_headers({}),
            "select sum(v) as s from t")
        assert qe.wait(60)
        assert qe.state == "FINISHED", qe.error
        with open(log) as fh:
            recs = [json.loads(line) for line in fh]
        assert recs
        rec = recs[-1]
        assert rec["queryId"] == qe.query_id
        assert rec["state"] == "FINISHED"
        # the trace's top spans ride along inline
        assert rec["topSpans"]
        assert all("durationS" in t for t in rec["topSpans"])
    finally:
        w.close()
        coord.close()


def test_local_runner_trace_and_disable():
    from presto_tpu.exec.runner import LocalRunner

    cat = _catalog()
    r = LocalRunner(cat)
    r.run("select k, sum(v) as s from t group by k")
    tr = r.last_trace
    assert tr is not None
    kinds = {s.kind for s in tr.spans()}
    assert "query" in kinds
    assert "operator" in kinds
    root = next(s for s in tr.spans() if s.span_id == tr.root_id)
    assert root.name == "query"
    # tracing off: NOOP end to end, nothing recorded
    r2 = LocalRunner(cat, ExecConfig(tracing=False))
    r2.run("select count(*) as n from t")
    assert r2.last_trace is None


# -- runtime statistics feedback plane (obs/runstats.py) -------------------


class TestRunstatsExposition:
    def test_drift_histogram_is_builtin(self):
        names = {h.name for h in obs_metrics.ALL_HISTOGRAMS}
        assert "presto_tpu_stats_drift_ratio" in names

    def test_hbo_families_on_metrics_endpoints(self, cluster):
        from presto_tpu.obs import runstats

        runstats.observe("fpT/cat", "agg_groups", "aggregate", 2.0, 8.0)
        for u in ([cluster.coordinator.url]
                  + [w.url for w in cluster.workers]):
            with urllib.request.urlopen(f"{u}/v1/metrics", timeout=10) as r:
                body = r.read().decode()
            assert lint_exposition(body) == []
            assert "presto_tpu_hbo_observations_total" in body
            assert "presto_tpu_hbo_history_entries" in body
            assert "presto_tpu_stats_drift_ratio_bucket" in body
            assert "presto_tpu_breaker_replay_waves_total" in body

    def test_mesh_emits_exchange_and_lane_spans(self):
        from presto_tpu.parallel.mesh import make_mesh
        from presto_tpu.parallel.mesh_exec import MeshExecutor

        cat = _catalog()
        mx = MeshExecutor(cat, make_mesh(8), ExecConfig())
        tr = obs_trace.Tracer()
        with obs_trace.use(tr):
            mx.run("select k, sum(v) as s from t group by k")
        kinds = {s.kind for s in tr.spans()}
        # PR 9's fused collectives bypass the tracer; the host-side
        # markers close that wall-time hole
        assert "mesh_program" in kinds
        assert "exchange_wait" in kinds
        assert "lane_pack" in kinds
        assert "breaker_engine" in kinds
        ew = next(s for s in tr.spans() if s.kind == "exchange_wait")
        assert {"fid", "bytes", "lanes_used", "lanes_total",
                "util"} <= set(ew.attrs)
        mp = next(s for s in tr.spans() if s.kind == "mesh_program")
        assert ew.parent_id == mp.span_id


def test_slow_query_logger_hbo_fields(tmp_path):
    p = str(tmp_path / "slow.jsonl")
    lg = SlowQueryLogger(p, threshold_s=0.0)
    spans = [
        obs_trace.Span("s1", None, "breaker_engine", "breaker_engine",
                       0.0, 0.0, {"node": "Aggregate", "engine": "sort",
                                  "why": "observed 6e+03 groups"}),
        obs_trace.Span("s2", None, "exchange f0", "exchange_wait",
                       0.0, 0.0, {"fid": 0, "lanes_used": 12,
                                  "lanes_total": 64, "util": 0.1875}),
        obs_trace.Span("s3", None, "overflow_replay", "overflow_replay",
                       0.0, 0.0, {"node": "Aggregate", "cap_to": 8192}),
        obs_trace.Span("s4", None, "overflow_replay", "overflow_replay",
                       0.0, 0.0, {"node": "HashJoin"}),
    ]
    lg.log(_qinfo(qid="q9", elapsed=1.0), spans)
    with open(p) as fh:
        rec = json.loads(fh.readlines()[-1])
    assert rec["breakerEngines"] == [
        {"node": "Aggregate", "engine": "sort",
         "why": "observed 6e+03 groups"}]
    assert rec["laneUtil"] == [
        {"fid": 0, "lanesUsed": 12, "lanesTotal": 64, "util": 0.1875}]
    assert rec["overflowReplays"] == 2
    assert rec["overflowBoosts"] == 1  # only the cap_to-carrying wave
