"""Mesh exchange plane: fused lane packing, stats-sized capacities, and
surgical per-site overflow replay (parallel/lanes.py + mesh_exec.py).

Three layers of checks:
- lane packer property matrix: pack → all_to_all → unpack must be
  bit-exact against the per-column exchange for every dtype / validity /
  hi / dict-column / ragged-row-count combination;
- surgical replay: a skew-adversarial one-hot join key with deliberately
  uniform stats overflows exactly ONE exchange site; the retry doubles
  only that site's capacity and the boost does not leak into later
  queries (the old executor-level _cap_boost regression);
- observability: per-run exchange meta (bytes, lanes, utilization,
  collective count) and the process metric counters.
"""

import numpy as np
import pandas as pd
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from presto_tpu.batch import Batch, Column
from presto_tpu.catalog.memory import MemoryConnector
from presto_tpu.catalog.tpch import tpch_catalog
from presto_tpu.connector import Catalog
from presto_tpu.exec import ExecConfig, LocalRunner
from presto_tpu.ops.partition import partition_for_exchange, partition_layout
from presto_tpu.parallel import lanes
from presto_tpu.parallel.mesh import WORKERS, make_mesh, shard_map
from presto_tpu.parallel.mesh_exec import (
    MeshExecutor,
    _all_to_all_batch,
    _fused_all_to_all,
)
from presto_tpu.scan import metrics as scan_metrics
from presto_tpu.types import BIGINT, BOOLEAN, DATE, DOUBLE, INTEGER, VARCHAR

N_DEV = 8


# ---------------------------------------------------------------------------
# lane packer property matrix (host-side, no mesh)


def _make_batch(rng, cap, *, with_validity, with_hi, with_dict):
    """A schema that spans the dtype buckets: int64, float64, int32 (date
    + dict codes), bool, plus optional validity and hi lanes."""
    names = ["k", "x", "d"]
    types = [BIGINT, DOUBLE, DATE]
    cols = [
        Column(jnp.asarray(rng.integers(0, 50, cap), jnp.int64),
               jnp.asarray(rng.random(cap) < 0.9) if with_validity else None),
        Column(jnp.asarray(rng.random(cap)),
               None,
               jnp.asarray(rng.integers(0, 3, cap), jnp.int64)
               if with_hi else None),
        Column(jnp.asarray(rng.integers(8000, 9000, cap), jnp.int32)),
    ]
    dicts = {}
    if with_dict:
        names.append("s")
        types.append(VARCHAR)
        dicts["s"] = ("alpha", "beta", "gamma")
        cols.append(Column(jnp.asarray(rng.integers(0, 3, cap), jnp.int32)))
    live = jnp.asarray(rng.random(cap) < 0.8)
    return Batch(names, types, cols, live, dicts)


@pytest.mark.parametrize("with_validity", [False, True])
@pytest.mark.parametrize("with_hi", [False, True])
@pytest.mark.parametrize("with_dict", [False, True])
@pytest.mark.parametrize("cap", [64, 96, 257])
def test_pack_unpack_roundtrip(with_validity, with_hi, with_dict, cap):
    rng = np.random.default_rng(cap * 8 + with_validity * 4
                                + with_hi * 2 + with_dict)
    b = _make_batch(rng, cap, with_validity=with_validity,
                    with_hi=with_hi, with_dict=with_dict)
    plan = lanes.plan_lanes(b)
    assert plan is not None
    # every plane gets exactly one lane; collectives = dtype buckets
    n_planes = 1 + sum(1 + (c.validity is not None) + (c.hi is not None)
                       for c in b.columns)
    assert len(plan.entries) == n_planes
    assert plan.n_collectives <= n_planes
    if with_validity or with_hi or with_dict:
        # duplicate dtypes share a bucket, so fusing beats per-plane
        assert plan.n_collectives < n_planes
    got = lanes.unpack_batch(b, plan, lanes.pack_batch(b, plan))
    assert got.names == b.names and got.dicts == b.dicts
    np.testing.assert_array_equal(np.asarray(got.live), np.asarray(b.live))
    for c0, c1 in zip(b.columns, got.columns):
        assert c1.values.dtype == c0.values.dtype
        np.testing.assert_array_equal(np.asarray(c1.values),
                                      np.asarray(c0.values))
        for p0, p1 in ((c0.validity, c1.validity), (c0.hi, c1.hi)):
            assert (p0 is None) == (p1 is None)
            if p0 is not None:
                np.testing.assert_array_equal(np.asarray(p1), np.asarray(p0))


@pytest.mark.parametrize("cap", [64, 200])
@pytest.mark.parametrize("with_validity,with_hi,with_dict",
                         [(False, False, False), (True, True, True),
                          (True, False, True)])
def test_pack_partitioned_matches_per_column(cap, with_validity, with_hi,
                                             with_dict):
    """The fused partition+pack scatter must equal partition_for_exchange
    followed by packing — same routing, same slots, same planes."""
    rng = np.random.default_rng(cap + with_validity + 2 * with_hi)
    b = _make_batch(rng, cap, with_validity=with_validity,
                    with_hi=with_hi, with_dict=with_dict)
    per_cap = max(cap // N_DEV, 16)
    plan = lanes.plan_lanes(b)
    sperm, dest, counts, routed, ovf = partition_layout(
        b, ["k"], N_DEV, per_cap)
    bufs = lanes.pack_partitioned(b, plan, sperm, dest, routed,
                                  N_DEV * per_cap)
    parts, counts2, ovf2 = partition_for_exchange(b, ["k"], N_DEV, per_cap)
    ref = lanes.pack_batch(parts, plan)
    np.testing.assert_array_equal(np.asarray(counts), np.asarray(counts2))
    assert int(ovf) == int(ovf2)
    for got, exp in zip(bufs, ref):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(exp))


def test_plan_lanes_declines_structural_columns():
    b = _make_batch(np.random.default_rng(0), 64, with_validity=True,
                    with_hi=False, with_dict=False)
    cols = list(b.columns)
    cols[0] = Column(cols[0].values, cols[0].validity,
                     sizes=jnp.zeros(64, jnp.int32))
    assert lanes.plan_lanes(Batch(b.names, b.types, cols, b.live,
                                  b.dicts)) is None


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(N_DEV)


def test_fused_all_to_all_matches_per_plane(mesh):
    """End-to-end on the 8-device mesh: partition → fused pack → one
    collective per bucket → unpack must be bit-exact vs the per-column
    all_to_all path, for ragged per-device row counts."""
    rng = np.random.default_rng(7)
    cap, per_cap = 96, 32
    shards = [_make_batch(rng, cap, with_validity=True, with_hi=True,
                          with_dict=True) for _ in range(N_DEV)]
    # ragged: each device keeps a different number of live rows
    shards = [b.with_live(b.live & (jnp.arange(cap) < 8 * (d + 3)))
              for d, b in enumerate(shards)]
    tpl = shards[0]
    glob = Batch(
        tpl.names, tpl.types,
        [Column(jnp.concatenate([s.columns[i].values for s in shards]),
                jnp.concatenate([s.columns[i].validity for s in shards])
                if tpl.columns[i].validity is not None else None,
                jnp.concatenate([s.columns[i].hi for s in shards])
                if tpl.columns[i].hi is not None else None)
         for i in range(len(tpl.columns))],
        jnp.concatenate([s.live for s in shards]), tpl.dicts)
    sh = NamedSharding(mesh, P(WORKERS))
    glob = Batch(glob.names, glob.types,
                 [Column(jax.device_put(c.values, sh),
                         None if c.validity is None
                         else jax.device_put(c.validity, sh),
                         None if c.hi is None else jax.device_put(c.hi, sh))
                  for c in glob.columns],
                 jax.device_put(glob.live, sh), glob.dicts)
    plan = lanes.plan_lanes(tpl)

    def both(b):
        sperm, dest, _counts, routed, _ovf = partition_layout(
            b, ["k"], N_DEV, per_cap)
        bufs = lanes.pack_partitioned(b, plan, sperm, dest, routed,
                                      N_DEV * per_cap)
        fused = lanes.unpack_batch(b, plan,
                                   _fused_all_to_all(bufs, N_DEV, per_cap))
        parts, _c, _o = partition_for_exchange(b, ["k"], N_DEV, per_cap)
        ref = _all_to_all_batch(parts, N_DEV, per_cap)
        return fused, ref

    fused, ref = jax.jit(shard_map(
        both, mesh=mesh, in_specs=(P(WORKERS),),
        out_specs=(P(WORKERS), P(WORKERS)), check_vma=False))(glob)
    np.testing.assert_array_equal(np.asarray(fused.live),
                                  np.asarray(ref.live))
    for cf, cr in zip(fused.columns, ref.columns):
        np.testing.assert_array_equal(np.asarray(cf.values),
                                      np.asarray(cr.values))
        if cr.validity is not None:
            np.testing.assert_array_equal(np.asarray(cf.validity),
                                          np.asarray(cr.validity))
        if cr.hi is not None:
            np.testing.assert_array_equal(np.asarray(cf.hi),
                                          np.asarray(cr.hi))


# ---------------------------------------------------------------------------
# surgical overflow replay + boost isolation


@pytest.fixture(scope="module")
def skew_env(mesh):
    conn = MemoryConnector()
    rng = np.random.default_rng(11)
    # one-hot join key: EVERY fact row carries k=3, so each device routes
    # all its rows into one exchange lane — worst-case skew
    conn.add_table("fact", pd.DataFrame({
        "k": np.full(800, 3, np.int64),
        "v": rng.integers(0, 1000, 800).astype(np.int64),
    }))
    conn.add_table("dim", pd.DataFrame({
        "k": np.arange(8, dtype=np.int64),
        "w": np.arange(8, dtype=np.int64) * 10,
    }))
    cat = Catalog()
    cat.register("m", conn, default=True)
    mx = MeshExecutor(cat, mesh, ExecConfig(batch_rows=1 << 12))
    return cat, mx


def _skew_dplan(cat):
    """Partitioned (OUT_HASH both sides) join plan with stats stamped as
    if the key were UNIFORM — the lie that makes stats-sized lanes
    under-provision the hot partition by exactly one doubling."""
    from presto_tpu.plan.builder import plan_query
    from presto_tpu.plan.fragmenter import OUT_HASH, fragment_plan
    from presto_tpu.plan.optimizer import optimize

    q = ("select sum(fact.v + dim.w) as s from fact, dim "
         "where fact.k = dim.k")
    qp = optimize(plan_query(q, cat), cat)
    # broadcast_threshold_rows=0 forces the PARTITIONED join shape
    dplan = fragment_plan(qp, cat, broadcast_threshold_rows=0.0)
    hash_fids = [fid for fid, f in dplan.fragments.items()
                 if f.output_partitioning == OUT_HASH]
    assert hash_fids, dplan.to_string()
    fact_fid = None
    for fid in hash_fids:
        f = dplan.fragments[fid]
        if f.est_rows and f.est_rows > 100:  # the 800-row fact side
            f.est_rows, f.est_key_ndv = 800.0, 800.0
            fact_fid = fid
    assert fact_fid is not None
    return dplan, fact_fid


def test_skew_triggers_exactly_one_surgical_retry(skew_env):
    cat, mx = skew_env
    dplan, fact_fid = _skew_dplan(cat)
    got = mx.run_dplan(dplan).to_pandas()
    # correctness first: the replayed query still matches the local engine
    exp = LocalRunner(cat).run(
        "select sum(fact.v + dim.w) as s from fact, dim "
        "where fact.k = dim.k")
    assert int(got["s"][0]) == int(exp["s"][0])

    lr = mx.last_run
    assert lr["retries"] == 1
    assert len(lr["attempts"]) == 2
    # exactly one site boosted, and it is the fact-side exchange
    (site, boost), = lr["boosts"].items()
    assert boost == 2
    labels = lr["attempts"][0]["labels"]
    assert labels[site] == ("exchange", fact_fid)
    # attempt 0 overflowed ONLY at that site
    ovf0 = lr["attempts"][0]["overflow"]
    assert ovf0[site] > 0
    assert all(v == 0 for i, v in enumerate(ovf0) if i != site)
    # the replay doubled that site's capacity and no other site got a
    # boost: every other site's cap is unchanged except join_out, whose
    # size is DERIVED from its probe input (the widened exchange) rather
    # than boosted — its own boost stays 1
    caps0 = lr["attempts"][0]["site_caps"]
    caps1 = lr["attempts"][1]["site_caps"]
    assert caps1[site] == 2 * caps0[site]
    assert all(c1 == c0 for i, (c0, c1) in enumerate(zip(caps0, caps1))
               if i != site and labels[i] != ("join_out",))
    # and the replay drained: no overflow anywhere on attempt 1
    assert all(v == 0 for v in lr["attempts"][1]["overflow"])


def test_boosts_do_not_leak_across_queries(skew_env):
    """Regression: the old executor kept a sticky _cap_boost that doubled
    EVERY later query's capacities after one overflow. Boosts must be
    per-run."""
    cat, mx = skew_env
    dplan, _ = _skew_dplan(cat)
    mx.run_dplan(dplan)
    assert mx.last_run["retries"] >= 1
    assert not hasattr(mx, "_cap_boost")
    # a well-sized query right after the overflow: fresh boosts, no retry,
    # and lane capacities at their unboosted size
    mx.run("select dim.k as k, sum(dim.w) as w from dim group by dim.k")
    assert mx.last_run["retries"] == 0
    assert mx.last_run["boosts"] == {}


# ---------------------------------------------------------------------------
# stats-sized lanes, program cache, metrics


@pytest.fixture(scope="module")
def tpch_mesh(mesh):
    cat = tpch_catalog(0.01)
    conn = cat.connectors["tpch"]
    for t in ("customer", "orders", "lineitem"):
        conn._ensure(t)
    mx = MeshExecutor(cat, mesh, ExecConfig(batch_rows=1 << 12,
                                            agg_capacity=1 << 10))
    return cat, mx


Q3 = """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
       o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate limit 10
"""


def test_q3_exchanges_fused_and_stats_sized(tpch_mesh):
    """Acceptance: the Q3-shaped pipeline's exchanges all ride the fused
    single-buffer path with zero retries, and stats sizing at least
    halves the allocated lanes vs the capacity//n_dev*2 rule (≥2× lane
    utilization at equal routed rows)."""
    scan_metrics.reset()
    cat, mx = tpch_mesh
    mx.run(Q3)
    lr = mx.last_run
    assert lr["retries"] == 0
    exchanges = lr["attempts"][0]["exchanges"]
    assert exchanges, "Q3 plan produced no OUT_HASH exchange"
    assert all(e["fused"] for e in exchanges)
    assert all(e["a2a"] < 8 for e in exchanges)  # O(buckets), not O(planes)
    assert any(2 * e["per_cap"] <= e["naive_per_cap"] for e in exchanges), \
        exchanges
    assert all(e["lanes_used"] <= e["lanes_total"] for e in exchanges)
    snap = scan_metrics.snapshot()
    assert snap["mesh_exchange_bytes"] > 0
    assert snap["mesh_exchange_lanes_total"] >= snap["mesh_exchange_lanes_used"] > 0
    assert snap["mesh_exchange_overflow_retries"] == 0
    # the rendered plan carries the exchange telemetry markers
    names = [r[0] for r in scan_metrics.metric_rows()]
    assert "presto_tpu_mesh_exchange_bytes_total" in names


def test_mesh_program_cache_reuses_trace(tpch_mesh):
    cat, mx = tpch_mesh
    mx.run(Q3)
    n_progs = len(mx._progs)
    traces = {k: e.meta["traces"] for k, e in mx._progs.items()}
    mx.run(Q3)
    assert len(mx._progs) == n_progs
    assert {k: e.meta["traces"] for k, e in mx._progs.items()} == traces


def test_mesh_plan_markers_rendered(tpch_mesh):
    from presto_tpu.plan.builder import plan_query
    from presto_tpu.plan.fragmenter import fragment_plan
    from presto_tpu.plan.optimizer import optimize

    cat, mx = tpch_mesh
    dplan = fragment_plan(optimize(plan_query(Q3, cat), cat), cat)
    mx.run_dplan(dplan)
    s = dplan.to_string()
    assert "[mesh: a2a=" in s and "util=" in s
