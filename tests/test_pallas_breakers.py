"""Pallas linear-probing breaker engine (ops/pallas_hash.py) and the
stats-driven hash-vs-sort CBO choice (plan/stats.choose_breaker_engine,
exec/runtime breaker_engine threading).

Kernel-level: insert/probe vs a numpy oracle across capacities,
collision-heavy and skew-adversarial key sets, int64 plane exactness,
overflow accounting. Engine-level: overflow→regrow replay end-to-end,
forced-hash TPC-H/TPC-DS verifier sweeps against the sort engine, the
CBO picking differently per breaker, EXPLAIN/metrics surfacing, and the
session property. Everything runs in interpret mode on CPU — bit-exact
with the compiled TPU kernels."""

import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from presto_tpu.catalog.memory import MemoryConnector
from presto_tpu.catalog.tpch import tpch_catalog
from presto_tpu.connector import Catalog
from presto_tpu.exec import ExecConfig, LocalRunner
from presto_tpu.ops import pallas_hash as ph
from presto_tpu.ops.hashing import hash_columns
from presto_tpu.ops.radix import slot_hash
from presto_tpu.verifier import Verifier, report

from conftest import assert_frames_match


# ---------------------------------------------------------------------------
# kernel oracle helpers


def _planes(*cols):
    return jnp.stack([ph.encode_plane(jnp.asarray(c)) for c in cols])


def _slot0(planes, tcap):
    return slot_hash(hash_columns(list(planes)), tcap)


def _group_oracle(rows, live):
    """row index -> oracle group label (first-seen order over live rows)."""
    seen = {}
    out = []
    for i, r in enumerate(rows):
        if not live[i]:
            out.append(None)
            continue
        out.append(seen.setdefault(r, len(seen)))
    return out, len(seen)


def _check_group_assignment(gid, rows, live, tcap):
    """gid must induce exactly the oracle partition: equal keys share a
    gid, distinct keys do not, dead rows park at tcap."""
    oracle, n_distinct = _group_oracle(rows, live)
    gid = np.asarray(gid)
    by_label = {}
    for i, lab in enumerate(oracle):
        if lab is None:
            assert gid[i] == tcap, f"dead row {i} got gid {gid[i]}"
            continue
        assert gid[i] < tcap, f"live row {i} unplaced"
        by_label.setdefault(lab, set()).add(int(gid[i]))
    assert all(len(s) == 1 for s in by_label.values()), \
        "one key split across gids"
    firsts = [next(iter(s)) for s in by_label.values()]
    assert len(set(firsts)) == n_distinct, "distinct keys collapsed"


# ---------------------------------------------------------------------------
# group insert vs oracle


@pytest.mark.parametrize("cap", [4, 16, 64, 256])
def test_group_insert_oracle_across_capacities(cap):
    rng = np.random.default_rng(cap)
    n = 4 * cap
    keys = rng.integers(0, cap, size=n).astype(np.int64)  # distinct <= cap
    live = rng.random(n) > 0.1
    planes = _planes(keys)
    tcap = 2 * cap
    gid, table, occ, ng, ovf = ph.group_insert(
        planes, _slot0(planes, tcap), jnp.asarray(live), cap,
        interpret=True)
    rows = [(int(k),) for k in keys]
    _check_group_assignment(gid, rows, live, tcap)
    _, n_distinct = _group_oracle(rows, live)
    assert int(ng) == n_distinct and int(ovf) == 0
    # the table's occupied slots reproduce exactly the distinct key set
    occ = np.asarray(occ)
    table = np.asarray(table)
    assert set(table[0][occ > 0]) == {k for k, l in zip(keys, live) if l}


def test_group_insert_collision_heavy_single_slot():
    """Every row lands on slot 0 — the worst probe chain the table can
    see; distinct keys must still separate via linear probing."""
    cap = 32
    keys = np.arange(24, dtype=np.int64) % 12
    live = np.ones(24, bool)
    planes = _planes(keys)
    gid, _, _, ng, ovf = ph.group_insert(
        planes, jnp.zeros(24, jnp.int32), jnp.asarray(live), cap,
        interpret=True)
    _check_group_assignment(gid, [(int(k),) for k in keys], live, 2 * cap)
    assert int(ng) == 12 and int(ovf) == 0


def test_group_insert_skew_adversarial():
    """90% one hot key + a long tail, nullable second key: the presto-ish
    skew shape radix alone does not fix."""
    rng = np.random.default_rng(7)
    n = 2048
    hot = rng.random(n) < 0.9
    k1 = np.where(hot, 42, rng.integers(0, 200, size=n)).astype(np.int64)
    k2 = rng.integers(0, 3, size=n).astype(np.int64)
    valid2 = rng.random(n) > 0.2
    live = rng.random(n) > 0.05
    planes, has_nulls = ph.encode_group_keys(
        [(jnp.asarray(k1), None), (jnp.asarray(k2), jnp.asarray(valid2))])
    assert has_nulls
    cap = 1024
    gid, _, _, ng, ovf = ph.group_insert(
        planes, _slot0(planes, 2 * cap), jnp.asarray(live), cap,
        interpret=True)
    rows = [(int(a), int(b) if v else None)
            for a, b, v in zip(k1, k2, valid2)]
    _check_group_assignment(gid, rows, live, 2 * cap)
    _, n_distinct = _group_oracle(rows, live)
    assert int(ng) == n_distinct and int(ovf) == 0


def test_group_insert_overflow_counts_unplaced_rows():
    cap = 8
    keys = np.arange(64, dtype=np.int64)  # 64 distinct >> cap
    planes = _planes(keys)
    gid, _, _, ng, ovf = ph.group_insert(
        planes, _slot0(planes, 2 * cap), jnp.ones(64, bool), cap,
        interpret=True)
    assert int(ng) == cap            # inserts stop at the logical budget
    assert int(ovf) == 64 - cap      # every unplaced row counted once
    assert int(np.sum(np.asarray(gid) == 2 * cap)) == 64 - cap


# ---------------------------------------------------------------------------
# plane encoding exactness


def test_encode_plane_int64_limbs_exact_near_2_62():
    vals = jnp.asarray([(1 << 62) - 1, -(1 << 62), (1 << 62) - 3,
                        (1 << 61) + 12345678901234567], jnp.int64)
    plane = ph.encode_plane(vals)
    np.testing.assert_array_equal(np.asarray(ph.decode_plane(
        plane, jnp.int64)), np.asarray(vals))
    # distinct giant values stay distinct groups
    gid, _, _, ng, ovf = ph.group_insert(
        jnp.stack([plane]), _slot0(jnp.stack([plane]), 16),
        jnp.ones(4, bool), 8, interpret=True)
    assert int(ng) == 4 and int(ovf) == 0


def test_encode_plane_float_identities():
    v = jnp.asarray([0.0, -0.0, 1.5, np.nan, np.nan], jnp.float64)
    p = np.asarray(ph.encode_plane(v))
    assert p[0] == p[1], "-0.0 must encode like +0.0"
    assert p[3] == p[4], "NaNs must canonicalize to one GROUP BY key"
    assert len({p[0], p[2], p[3]}) == 3
    # join planes keep NaN distinct-from-everything via the matchable
    # mask, not the plane; canonicalize_nan=False leaves bits alone
    q = np.asarray(ph.encode_plane(v, canonicalize_nan=False))
    assert q[0] == q[1]


# ---------------------------------------------------------------------------
# join insert/probe vs oracle


def _join_tables(bkeys, blive, tcap):
    planes = _planes(bkeys)
    slot0 = _slot0(planes, tcap)
    slot_row = ph.join_insert(slot0, jnp.asarray(blive), tcap,
                              interpret=True)
    return planes, slot_row


def _probe_oracle(bkeys, blive, pkeys, plive):
    out = {}
    for i, (k, l) in enumerate(zip(pkeys, plive)):
        if not l:
            out[i] = []
            continue
        out[i] = [j for j, (bk, bl) in enumerate(zip(bkeys, blive))
                  if bl and bk == k]
    return out


@pytest.mark.parametrize("tcap", [64, 256, 1024])
def test_join_probe_oracle_counts_exact(tcap):
    rng = np.random.default_rng(tcap)
    nb, np_ = tcap // 4, tcap // 2
    bkeys = rng.integers(0, nb // 2, size=nb).astype(np.int64)
    blive = rng.random(nb) > 0.15
    pkeys = rng.integers(0, nb, size=np_).astype(np.int64)
    plive = rng.random(np_) > 0.1
    bplanes, slot_row = _join_tables(bkeys, blive, tcap)
    pplanes = _planes(pkeys)
    mm, cnt, ovf = ph.join_probe(
        _slot0(pplanes, tcap), pplanes, jnp.asarray(plive), slot_row,
        bplanes, fanout=8, interpret=True)
    oracle = _probe_oracle(bkeys, blive, pkeys, plive)
    cnt, mm = np.asarray(cnt), np.asarray(mm)
    n_over = 0
    for i, want in oracle.items():
        assert cnt[i] == len(want), f"row {i}: count {cnt[i]} != {len(want)}"
        got = [x for x in mm[i] if x >= 0]
        assert set(got) <= set(want) and len(got) == min(len(want), 8)
        n_over += len(want) > 8
    assert int(ovf) == n_over


def test_join_probe_collision_heavy_all_one_slot():
    bkeys = np.array([5, 9, 5, 13, 9, 5], np.int64)
    blive = np.ones(6, bool)
    tcap = 16
    bplanes = _planes(bkeys)
    slot_row = ph.join_insert(jnp.zeros(6, jnp.int32), jnp.asarray(blive),
                              tcap, interpret=True)
    pkeys = np.array([5, 9, 13, 7], np.int64)
    pplanes = _planes(pkeys)
    mm, cnt, ovf = ph.join_probe(
        jnp.zeros(4, jnp.int32), pplanes, jnp.ones(4, bool), slot_row,
        bplanes, fanout=4, interpret=True)
    oracle = _probe_oracle(bkeys, blive, pkeys, np.ones(4, bool))
    for i in range(4):
        assert int(np.asarray(cnt)[i]) == len(oracle[i])
        assert set(int(x) for x in np.asarray(mm)[i] if x >= 0) \
            == set(oracle[i])
    assert int(ovf) == 0


def test_join_probe_fanout_overflow_exact_counts():
    """counts stay EXACT past the fanout — that is the widening-ladder
    contract the runtime's re-probe depends on."""
    bkeys = np.full(12, 3, np.int64)  # one key, 12 duplicates
    tcap = 32
    bplanes, slot_row = _join_tables(bkeys, np.ones(12, bool), tcap)
    pplanes = _planes(np.array([3, 4], np.int64))
    mm, cnt, ovf = ph.join_probe(
        _slot0(pplanes, tcap), pplanes, jnp.ones(2, bool), slot_row,
        bplanes, fanout=4, interpret=True)
    assert int(np.asarray(cnt)[0]) == 12 and int(np.asarray(cnt)[1]) == 0
    assert int(ovf) == 1
    assert sorted(x for x in np.asarray(mm)[0] if x >= 0).__len__() == 4


# ---------------------------------------------------------------------------
# engine end-to-end: regrow replay, CBO, EXPLAIN, metrics, property


def _memory_catalog(n=3000, n_keys=600, seed=3):
    rng = np.random.default_rng(seed)
    conn = MemoryConnector()
    g = rng.integers(0, n_keys, size=n)
    v = rng.normal(0.0, 10.0, n)
    conn.add_table("t", pd.DataFrame({
        "g": g, "v": v, "s": [f"s{int(x) % 5}" for x in g]}))
    conn.add_table("d", pd.DataFrame({
        "k": np.arange(n_keys), "name": [f"n{i}" for i in range(n_keys)]}))
    cat = Catalog()
    cat.register("mem", conn, default=True)
    return cat


def test_hash_agg_overflow_regrows_and_matches_sort():
    """600 distinct keys through a 64-slot initial table: the overflow
    counter must drive the regrow-replay ladder to the same answer the
    sort engine produces."""
    cat = _memory_catalog()
    sql = "select g, count(*) c, sum(v) s from t group by g"
    base = dict(batch_rows=512, agg_capacity=64)
    hash_r = LocalRunner(cat, ExecConfig(breaker_engine="hash", **base))
    sort_r = LocalRunner(cat, ExecConfig(breaker_engine="sort", **base))
    assert_frames_match(hash_r.run(sql), sort_r.run(sql))
    assert hash_r.last_stats.get("breaker.engine_hash", 0) >= 1
    assert hash_r.last_stats.get("breaker.engine_sort", 0) == 0


def test_hash_join_matches_sort_engine():
    cat = _memory_catalog()
    sql = ("select d.name, count(*) c, sum(t.v) s from t "
           "join d on t.g = d.k group by d.name")
    base = dict(batch_rows=512)
    hash_r = LocalRunner(cat, ExecConfig(breaker_engine="hash", **base))
    sort_r = LocalRunner(cat, ExecConfig(breaker_engine="sort", **base))
    assert_frames_match(hash_r.run(sql), sort_r.run(sql))
    assert hash_r.last_stats.get("breaker.engine_hash", 0) >= 2


def test_auto_mode_cbo_picks_both_engines():
    """Low-duplication breakers must go sort, high-duplication hash — in
    auto mode BOTH dispatch counters end up non-zero."""
    from presto_tpu.scan import metrics as sm

    cat = tpch_catalog(0.01)
    r = LocalRunner(cat, ExecConfig(batch_rows=1 << 13))
    before = sm.snapshot()
    r.run("select l_returnflag, count(*) c from lineitem "
          "group by l_returnflag")
    assert r.last_stats.get("breaker.engine_hash", 0) == 1
    r.run("select l_orderkey, count(*) c from lineitem "
          "group by l_orderkey")
    assert r.last_stats.get("breaker.engine_sort", 0) == 1
    after = sm.snapshot()
    assert after["breaker_dispatches_hash"] > before["breaker_dispatches_hash"]
    assert after["breaker_dispatches_sort"] > before["breaker_dispatches_sort"]


def test_explain_shows_engine_choice():
    cat = _memory_catalog()
    auto = LocalRunner(cat, ExecConfig(batch_rows=512))
    out = auto.explain_analyze("select g, count(*) c from t group by g")
    assert "engine=hash" in out or "engine=sort" in out
    forced = LocalRunner(cat, ExecConfig(batch_rows=512,
                                         breaker_engine="hash"))
    out2 = forced.explain_analyze("select g, count(*) c from t group by g")
    assert "engine=hash: session breaker_engine=hash" in out2


def test_breaker_engine_session_property():
    from presto_tpu.server.session import Session, SessionPropertyError

    s = Session()
    assert s.exec_config().breaker_engine == "auto"
    s.set("breaker_engine", "HASH")
    assert s.exec_config().breaker_engine == "hash"
    with pytest.raises(SessionPropertyError):
        s.set("breaker_engine", "quantum")


# ---------------------------------------------------------------------------
# forced-hash verifier sweeps vs the sort engine


@pytest.fixture(scope="module")
def tpch_engines():
    cat = tpch_catalog(0.01)
    control = LocalRunner(cat, ExecConfig(batch_rows=1 << 13,
                                          breaker_engine="sort"))
    test = LocalRunner(cat, ExecConfig(batch_rows=1 << 13,
                                       breaker_engine="hash"))
    return control, test


def _tpch_queries():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "tpch_queries", os.path.join(os.path.dirname(__file__),
                                     "test_tpch.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.QUERIES


def test_tpch_subset_hash_matches_sort(tpch_engines):
    """Non-slow representative subset: agg-only (q1), join-heavy (q3),
    filter+agg (q6), outer-join agg (q13), large-fanout agg (q18)."""
    control, test = tpch_engines
    queries = _tpch_queries()
    picks = [(k, queries[k]) for k in ("q1", "q3", "q6", "q13", "q18")]
    v = Verifier(control, test)
    outcomes = v.run_suite(picks)
    assert all(o.ok for o in outcomes), report(outcomes)


@pytest.mark.slow
def test_tpch_sweep_hash_matches_sort(tpch_engines):
    control, test = tpch_engines
    queries = _tpch_queries()
    v = Verifier(control, test)
    outcomes = v.run_suite(sorted(queries.items(),
                                  key=lambda kv: int(kv[0][1:])))
    assert all(o.ok for o in outcomes), report(outcomes)


@pytest.mark.slow
def test_tpcds_sweep_hash_matches_sort():
    from presto_tpu.catalog.tpcds import tpcds_catalog

    from test_tpcds_answers import Q

    cat = tpcds_catalog(0.005)
    cfg = dict(batch_rows=1 << 13, agg_capacity=1 << 12)
    control = LocalRunner(cat, ExecConfig(breaker_engine="sort", **cfg))
    test = LocalRunner(cat, ExecConfig(breaker_engine="hash", **cfg))
    v = Verifier(control, test)
    outcomes = v.run_suite(list(Q.items()))
    assert all(o.ok for o in outcomes), report(outcomes)
