"""Writes v1: CREATE TABLE AS / INSERT INTO / DROP TABLE against the
memory and parquet connectors, with sqlite as the cross-engine oracle.

Reference: execution/CreateTableTask.java + DropTableTask, the
TableWriterOperator → TableFinishOperator chain (rows-written result),
MemoryPageSinkProvider and HivePageSink.
"""

import sqlite3

import numpy as np
import pandas as pd
import pytest

from presto_tpu.catalog.memory import MemoryConnector
from presto_tpu.catalog.parquet import ParquetConnector
from presto_tpu.connector import Catalog
from presto_tpu.exec import ExecConfig, LocalRunner
from presto_tpu.types import DecimalType


@pytest.fixture()
def env(tmp_path):
    rng = np.random.default_rng(9)
    n = 5_000
    df = pd.DataFrame({
        "g": rng.integers(0, 20, n),
        "s": rng.choice(["ash", "bay", "elm", None], n),
        "v": np.round(rng.random(n) * 100, 2),
    })
    conn = MemoryConnector()
    conn.add_table("t", df)
    cat = Catalog()
    cat.register("m", conn, default=True)
    cat.register("pq", ParquetConnector(str(tmp_path)))
    runner = LocalRunner(cat, ExecConfig(batch_rows=1 << 10))
    db = sqlite3.connect(":memory:")
    df.to_sql("t", db, index=False)
    return runner, db, conn


def _compare(runner, db, sql):
    got = runner.run(sql)
    cur = db.execute(sql)
    exp = pd.DataFrame(cur.fetchall(), columns=[d[0] for d in cur.description])
    assert len(got) == len(exp)
    for c in got.columns:
        g = [None if v is None or v != v else v for v in got[c]]
        e = [None if v is None or v != v else v for v in exp[c]]
        if g and isinstance(next((x for x in g if x is not None), None), float):
            assert all((a is None) == (b is None) or abs(a - b) < 1e-6
                       for a, b in zip(sorted(g, key=str), sorted(e, key=str)))
        else:
            assert sorted(map(str, g)) == sorted(map(str, e)), c


def test_ctas_round_trip_vs_sqlite(env):
    runner, db, _ = env
    out = runner.run("create table agg as "
                     "select g, count(*) as c, sum(v) as sv from t group by g")
    db.execute("create table agg as "
               "select g, count(*) as c, sum(v) as sv from t group by g")
    assert out.rows[0] == 20
    _compare(runner, db, "select g, c from agg order by g")


def test_insert_appends(env):
    runner, db, _ = env
    runner.run("create table cp as select g, v from t")
    db.execute("create table cp as select g, v from t")
    runner.run("insert into cp select g + 100 as g, v from t")
    db.execute("insert into cp select g + 100 as g, v from t")
    _compare(runner, db, "select count(*) as c, min(g) as lo, max(g) as hi from cp")


def test_insert_schema_mismatch_rejected(env):
    runner, _, _ = env
    runner.run("create table one as select g from t")
    with pytest.raises(Exception):
        runner.run("insert into one select g, v from t")


def test_ctas_strings_and_nulls(env):
    runner, db, _ = env
    runner.run("create table st as select s, count(*) as c from t group by s")
    db.execute("create table st as select s, count(*) as c from t group by s")
    _compare(runner, db, "select s, c from st")


def test_drop_table(env):
    runner, _, conn = env
    runner.run("create table dead as select g from t")
    assert "dead" in conn.tables
    runner.run("drop table dead")
    assert "dead" not in conn.tables
    runner.run("drop table if exists dead")  # no-op
    with pytest.raises(Exception):
        runner.run("drop table dead")


def test_parquet_ctas_and_insert(env):
    runner, db, _ = env
    out = runner.run("create table pq.w as select g, sum(v) as sv from t group by g")
    assert out.rows[0] == 20
    db.execute("create table w as select g, sum(v) as sv from t group by g")
    got = runner.run("select g, sv from pq.w order by g")
    cur = db.execute("select g, sv from w order by g")
    exp = pd.DataFrame(cur.fetchall(), columns=["g", "sv"])
    assert list(got.g) == list(exp.g)
    assert all(abs(float(a) - b) < 1e-6 for a, b in zip(got.sv, exp.sv))
    runner.run("insert into pq.w select g + 50 as g, sum(v) as sv from t group by g")
    assert len(runner.run("select * from pq.w")) == 40


def test_parquet_long_decimal_round_trip(env):
    runner, _, conn = env
    conn.add_generated("big", {
        "g": np.array([0, 0, 1]),
        "d": ("raw_decimal", DecimalType(15, 2),
              np.array([1 << 40, 1 << 41, 7])),
    })
    runner.run("create table pq.bd as select g, sum(d) as sd from big group by g")
    back = runner.run("select g, sd from pq.bd order by g")
    assert int(back.sd[0].scaleb(2)) == (1 << 40) + (1 << 41)
    assert int(back.sd[1].scaleb(2)) == 7


def test_ctas_then_query_joins_against_it(env):
    runner, db, _ = env
    runner.run("create table gsum as select g, sum(v) as sv from t group by g")
    db.execute("create table gsum as select g, sum(v) as sv from t group by g")
    _compare(runner, db,
             "select t.g, count(*) as c from t join gsum on t.g = gsum.g "
             "group by t.g order by t.g")


def test_distributed_ctas(env):
    from presto_tpu.server.coordinator import DistributedRunner

    runner, db, _ = env
    dist = DistributedRunner(runner.catalog, n_workers=2,
                             config=ExecConfig(batch_rows=1 << 10))
    try:
        out = dist.run("create table dagg as "
                       "select g, count(*) as c from t group by g")
        assert out.rows[0] == 20
        back = dist.run("select count(*) as n from dagg")
        assert back.n[0] == 20
    finally:
        dist.close()


class TestViewsAndDelete:
    """Views, DELETE, TRUNCATE, CREATE TABLE (schema) — the wider DDL
    surface (CreateViewTask / DeleteNode-rewrite / TruncateTableTask)."""

    @pytest.fixture()
    def r(self):
        conn = MemoryConnector()
        conn.add_table("t", {"g": np.arange(20) % 4,
                             "v": np.arange(20.0)})
        cat = Catalog()
        cat.register("m", conn, default=True)
        return LocalRunner(cat, ExecConfig())

    def test_create_and_query_view(self, r):
        r.run("create view big as select g, v from t where v >= 10")
        df = r.run("select g, count(*) as n from big group by g order by g")
        assert df.n.tolist() == [2, 2, 3, 3]
        # views compose with further filters and joins
        df2 = r.run("select count(*) as n from big where g = 1")
        assert df2.n[0] == 2  # v in {13, 17}

    def test_or_replace_and_drop_view(self, r):
        r.run("create view x as select g from t")
        with pytest.raises(Exception):
            r.run("create view x as select v from t")
        r.run("create or replace view x as select v from t where v < 5")
        assert r.run("select count(*) as n from x").n[0] == 5
        r.run("drop view x")
        with pytest.raises(Exception):
            r.run("select * from x")
        r.run("drop view if exists x")  # no error

    def test_delete_where(self, r):
        out = r.run("delete from t where v < 5")
        assert out.rows[0] == 5
        assert r.run("select count(*) as n from t").n[0] == 15
        # NULL predicate keeps the row: nullif(v,v) is always NULL
        out = r.run("delete from t where nullif(v, v) > 0")
        assert out.rows[0] == 0
        assert r.run("select count(*) as n from t").n[0] == 15

    def test_truncate_and_create_schema(self, r):
        r.run("truncate table t")
        assert r.run("select count(*) as n from t").n[0] == 0
        r.run("create table fresh (a bigint, b varchar, c double)")
        assert r.run("select count(*) as n from fresh").n[0] == 0
        r.run("insert into fresh select g, 'x', v from t")  # empty insert
        r2 = r.run("select count(*) as n from fresh")
        assert r2.n[0] == 0
        # decimal schema columns round-trip too
        r.run("create table money (a decimal(10,2))")
        assert r.run("select count(*) as n from money").n[0] == 0

    def test_parquet_delete_truncate(self, tmp_path):
        from presto_tpu.catalog.parquet import ParquetConnector

        conn = ParquetConnector(str(tmp_path))
        cat = Catalog()
        cat.register("pq", conn, default=True)
        r = LocalRunner(cat, ExecConfig())
        r.run("create table t as select * from "
              "(values (1, 'a'), (2, 'b'), (3, 'c')) as v(k, s)")
        out = r.run("delete from t where k <= 2")
        assert out.rows[0] == 2
        assert r.run("select count(*) as n from t").n[0] == 1
        r.run("truncate table t")
        assert r.run("select count(*) as n from t").n[0] == 0
        r.run("create table empty2 (x double)")
        assert r.run("select count(*) as n from empty2").n[0] == 0


class TestScaledWriters:
    """Distributed CTAS into parquet writes per-task part files
    (SCALED_WRITER_DISTRIBUTION + TableWriter/TableFinish analog)."""

    def test_scaled_ctas_parts_and_readback(self, tmp_path):
        import os

        from presto_tpu.server.coordinator import DistributedRunner

        rng = np.random.default_rng(23)
        n = 20_000
        src = MemoryConnector()
        src.add_table("t", pd.DataFrame({
            "g": rng.integers(0, 50, n),
            "s": rng.choice(["ash", "bay", "elm"], n),
            "v": rng.normal(size=n).round(4),
        }))
        cat = Catalog()
        cat.register("m", src, default=True)
        cat.register("pq", ParquetConnector(str(tmp_path)))
        dist = DistributedRunner(cat, n_workers=2,
                                 config=ExecConfig(batch_rows=1 << 12))
        try:
            out = dist.run("create table pq.w as select g, s, v from t")
            assert out.rows[0] == n
            parts_dir = os.path.join(str(tmp_path), "w.parts")
            assert os.path.isdir(parts_dir)
            parts = [f for f in os.listdir(parts_dir)
                     if f.endswith(".parquet")]
            assert len(parts) >= 2  # one per writer task

            back = dist.run("select count(*) as n, sum(v) as sv, "
                            "count(distinct s) as ds from pq.w")
            assert back.n[0] == n
            assert back.ds[0] == 3
            exact = src.tables["t"].arrays["v"].sum()
            assert abs(float(back.sv[0]) - exact) < 1e-6
            # group-by over the part table matches the source
            a = dist.run("select g, count(*) as c from pq.w group by g "
                         "order by g")
            b = dist.run("select g, count(*) as c from t group by g "
                         "order by g")
            assert a.c.tolist() == b.c.tolist()
            dist.run("drop table pq.w")
            assert not os.path.isdir(parts_dir)
        finally:
            dist.close()

    def test_scaled_ctas_if_not_exists(self, tmp_path):
        from presto_tpu.server.coordinator import DistributedRunner

        src = MemoryConnector()
        src.add_table("t", pd.DataFrame({"x": np.arange(10)}))
        cat = Catalog()
        cat.register("m", src, default=True)
        cat.register("pq", ParquetConnector(str(tmp_path)))
        dist = DistributedRunner(cat, n_workers=2,
                                 config=ExecConfig(batch_rows=1 << 12))
        try:
            dist.run("create table pq.x as select x from t")
            out = dist.run("create table if not exists pq.x as "
                           "select x from t")
            assert out.rows[0] == 0
            with pytest.raises(Exception):
                dist.run("create table pq.x as select x from t")
            assert dist.run("select count(*) as n from pq.x").n[0] == 10
        finally:
            dist.close()

    def test_insert_into_part_table_appends_part(self, tmp_path):
        import os

        from presto_tpu.server.coordinator import DistributedRunner

        src = MemoryConnector()
        src.add_table("t", pd.DataFrame({"x": np.arange(100),
                                         "v": np.arange(100.0)}))
        cat = Catalog()
        cat.register("m", src, default=True)
        cat.register("pq", ParquetConnector(str(tmp_path)))
        dist = DistributedRunner(cat, n_workers=2,
                                 config=ExecConfig(batch_rows=1 << 12))
        try:
            dist.run("create table pq.p as select x, v from t")
            before = len(os.listdir(os.path.join(str(tmp_path), "p.parts")))
            out = dist.run("insert into pq.p select x + 100 as x, v from t")
            assert out.rows[0] == 100
            after = len(os.listdir(os.path.join(str(tmp_path), "p.parts")))
            assert after == before + 1  # appended one part, no rewrite
            back = dist.run("select count(*) as n, max(x) as mx from pq.p")
            assert back.n[0] == 200 and back.mx[0] == 199
        finally:
            dist.close()

    def test_part_table_footer_pruning(self, tmp_path):
        from presto_tpu.server.coordinator import DistributedRunner

        src = MemoryConnector()
        src.add_table("t", pd.DataFrame({"k": np.arange(10_000),
                                         "v": np.arange(10_000.0)}))
        cat = Catalog()
        pqc = ParquetConnector(str(tmp_path))
        cat.register("m", src, default=True)
        cat.register("pq", pqc)
        dist = DistributedRunner(cat, n_workers=2,
                                 config=ExecConfig(batch_rows=1 << 11))
        try:
            dist.run("create table pq.p as select k, v from t")
            h = pqc.get_table("p")
            splits = pqc.splits(h, 8)
            pruned = pqc.prune_splits(h, splits, {"k": (9_990, None)})
            assert 0 < len(pruned) < len(splits)  # footer stats pruned parts
            got = dist.run("select count(*) as n from pq.p where k >= 9990")
            assert got.n[0] == 10
        finally:
            dist.close()
