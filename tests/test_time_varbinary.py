"""TIME and VARBINARY types.

Reference: spi/type/TimeType (time-of-day), spi/type/VarbinaryType +
operator/scalar/VarbinaryFunctions.java. TPU-native shape: TIME is int64
microseconds-of-day (plain device arithmetic); VARBINARY rides the
latin-1 bijection through the VARCHAR dictionary machinery, so byte
equality/order/length need no new kernels."""

import numpy as np
import pandas as pd
import pytest

from presto_tpu.catalog.memory import MemoryConnector
from presto_tpu.connector import Catalog
from presto_tpu.exec import ExecConfig, LocalRunner


@pytest.fixture(scope="module")
def runner():
    conn = MemoryConnector()
    conn.add_table("shifts", pd.DataFrame({
        "worker": ["a", "b", "c", "d"],
        # micros of day: 08:30:00, 12:00:00, 23:59:59, 00:15:30
        "start": np.array([30600, 43200, 86399, 930], np.int64) * 1_000_000,
    }), types={"start": __import__("presto_tpu.types",
                                   fromlist=["TIME"]).TIME})
    conn.add_table("blobs", pd.DataFrame({
        "k": [1, 2, 3],
        "data": [b"hello", b"\x00\xff\x10", b"caf\xc3\xa9"],
    }))
    cat = Catalog()
    cat.register("m", conn, default=True)
    return LocalRunner(cat, ExecConfig(batch_rows=256))


def test_time_literals_compare_and_extract(runner):
    got = runner.run("""
        select worker, hour(start) as h, minute(start) as m,
               extract(second from start) as s
        from shifts where start >= time '08:30:00'
        order by start""")
    assert got.worker.tolist() == ["a", "b", "c"]
    assert got.h.tolist() == [8, 12, 23]
    assert got.m.tolist() == [30, 0, 59]
    assert got.s.tolist() == [0, 0, 59]


def test_time_fractional_literal_and_minmax(runner):
    got = runner.run("select min(start) as lo, max(start) as hi from shifts "
                     "where start < time '12:00:00.000001'")
    assert int(got.lo[0]) == 930 * 1_000_000
    assert int(got.hi[0]) == 43200 * 1_000_000


def test_varbinary_roundtrip_and_length(runner):
    got = runner.run("select k, data, length(data) as n from blobs order by k")
    assert got.data.tolist() == [b"hello", b"\x00\xff\x10", b"caf\xc3\xa9"]
    assert got.n.tolist() == [5, 3, 5]  # BYTE count, not codepoints


def test_hex_utf8_conversions(runner):
    got = runner.run("""
        select to_hex(data) as hx,
               from_utf8(data) as s,
               to_hex(from_hex(to_hex(data))) as rt
        from blobs order by k""")
    assert got.hx.tolist() == ["68656C6C6F", "00FF10", "636166C3A9"]
    assert got.s.tolist() == ["hello", "\x00�\x10", "café"]
    assert got.rt.tolist() == got.hx.tolist()


def test_binary_digest(runner):
    import hashlib

    got = runner.run("select to_hex(sha256(data)) as d from blobs "
                     "where k = 1")
    want = hashlib.sha256(b"hello").hexdigest().upper()
    assert got.d[0] == want
    # varchar overload still returns lowercase hex TEXT (extension)
    got2 = runner.run("select sha256(worker) as d from shifts "
                      "where worker = 'a'")
    assert got2.d[0] == hashlib.sha256(b"a").hexdigest()


def test_varbinary_group_and_join(runner):
    """Bytes behave as first-class values through group-by and joins."""
    got = runner.run("""
        select b1.data as d, count(*) as c
        from blobs b1 join blobs b2 on b1.data = b2.data
        group by b1.data order by c desc, d""")
    assert len(got) == 3
    assert got.c.tolist() == [1, 1, 1]


def test_to_utf8(runner):
    got = runner.run("select to_hex(to_utf8(worker)) as h from shifts "
                     "where worker = 'a'")
    assert got.h[0] == "61"
