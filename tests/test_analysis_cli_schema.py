"""One JSON contract for every analysis pass.

CI and external tooling parse `python -m presto_tpu.analysis --json`;
each plane (kernel lint, plan invariants, recompile guard,
concurrency, knob-flow, stale-suppressions) must emit the same
top-level document and the same finding record, so a consumer written
against one pass reads them all.
"""

import json
import textwrap

import pytest

from presto_tpu.analysis.__main__ import main

TOP_KEYS = {"findings", "count", "planes", "timings"}
FINDING_KEYS = {"rule", "loc", "message", "plane"}


def _run_json(argv, capsys):
    rc = main(argv + ["--json"])
    doc = json.loads(capsys.readouterr().out)
    return rc, doc


def _assert_schema(doc):
    assert set(doc) == TOP_KEYS
    assert doc["count"] == len(doc["findings"])
    assert isinstance(doc["planes"], list) and doc["planes"]
    assert set(doc["timings"]) == set(doc["planes"])
    for name, secs in doc["timings"].items():
        assert isinstance(secs, float) and secs >= 0.0, name
    for f in doc["findings"]:
        assert set(f) == FINDING_KEYS
        assert ":" in f["loc"]


# whole-package scans are exercised by ci.sh --all and the
# tests/test_knob_flow.py clean-tree tests; the schema matrix scopes
# the interprocedural passes to two packages to stay cheap
_SCOPE = ["presto_tpu/server", "presto_tpu/obs"]

CASES = {
    "lint": [],
    "concurrency": ["--no-lint", "--concurrency"] + _SCOPE,
    "knob-flow": ["--no-lint", "--knob-flow"] + _SCOPE,
    "stale-suppressions": ["--no-lint", "--stale-suppressions"] + _SCOPE,
    "plan": ["--no-lint", "--tpch-plans"],
    "recompile": ["--no-lint", "--tpch-run", "q6"],
}


@pytest.mark.parametrize("name", sorted(CASES))
def test_every_pass_emits_uniform_schema(name, capsys):
    rc, doc = _run_json(CASES[name], capsys)
    assert rc == 0, doc["findings"]
    _assert_schema(doc)
    assert doc["findings"] == []


def test_findings_share_one_record_shape(tmp_path, capsys):
    """A pass WITH findings still honours the schema, exits 1, and the
    plane tag matches the producing checker."""
    (tmp_path / "m.py").write_text(textwrap.dedent("""\
        import os

        import jax


        @jax.jit
        def kernel(x):
            return x if os.environ.get("PRESTO_TPU_TURBO") else -x
    """))
    rc, doc = _run_json(
        ["--no-lint", "--knob-flow", str(tmp_path / "m.py")], capsys)
    assert rc == 1
    _assert_schema(doc)
    assert [f["rule"] for f in doc["findings"]] == ["unfingerprinted-knob"]
    assert doc["findings"][0]["plane"] == "knob-flow"
    assert doc["findings"][0]["loc"].endswith("m.py:8")


@pytest.mark.slow  # ~60s; ci.sh runs --all directly on every push
def test_all_passes_mode_times_each_plane(capsys):
    rc, doc = _run_json(["--all"], capsys)
    assert rc == 0, doc["findings"]
    _assert_schema(doc)
    # the consolidated CI entrypoint covers every plane in one document
    labels = " ".join(doc["planes"])
    for want in ("lint", "concurrency", "knob-flow",
                 "stale-suppressions", "tpch plan invariants",
                 "tpch recompile guard"):
        assert want in labels, doc["planes"]


def test_knobs_json_document(capsys):
    rc = main(["--knobs", "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert set(doc) == {"knobs"}
    for row in doc["knobs"]:
        assert set(row) == {"knob", "kind", "lowers_to", "class",
                            "fingerprinted"}
    kinds = {r["kind"] for r in doc["knobs"]}
    assert kinds == {"session", "config", "env"}
